"""Serving metrics: latency percentiles, batch occupancy, queue depth, sheds.

The serving loop is host-threaded (the device does the math; the host does the
coalescing), so the interesting health signals are host-side: how long a
request waits end-to-end, how full the batches the batcher manages to build
are (occupancy == useful rows / padded rows is the padding tax; useful rows /
batches is the coalescing win), how deep the queue runs, and how often the
server sheds under overload.  Rows go through the same
``utils.logging.MetricsLogger`` JSONL surface as training metrics, so one
consumer reads both.
"""

from __future__ import annotations

import collections
import threading
from typing import Any, Dict, Optional

import numpy as np

from rainbow_iqn_apex_tpu.obs import registry as obs_registry
from rainbow_iqn_apex_tpu.utils.logging import MetricsLogger


class ServeMetrics:
    """Thread-safe rolling aggregation of per-request / per-batch stats.

    One instance is shared by the batcher (enqueue/shed), the worker (batch
    stats, request completion latencies) and the swap watcher (swap events);
    ``emit`` snapshots-and-resets the rolling window into one JSONL row.

    Backed by the shared obs/ MetricRegistry (role "serve"): every recording
    mirrors into registry counters/histograms so the /metrics exposition and
    the JSONL rows read the same numbers.  The window/percentile logic (and
    the whole ``record_*``/``emit``/``stats`` API) is unchanged — the
    registry is an additional sink, not a replacement surface.
    """

    def __init__(
        self,
        logger: Optional[MetricsLogger] = None,
        latency_window: int = 65536,
        registry: Optional[obs_registry.MetricRegistry] = None,
    ):
        self.logger = logger
        self.registry = registry if registry is not None else obs_registry.get()
        self._c_requests = self.registry.counter("serve_requests_total", "serve")
        self._c_shed = self.registry.counter("serve_shed_total", "serve")
        self._c_cancelled = self.registry.counter("serve_cancelled_total", "serve")
        self._c_batches = self.registry.counter("serve_batches_total", "serve")
        self._c_swaps = self.registry.counter("serve_swaps_total", "serve")
        self._c_padded = self.registry.counter("serve_padded_rows_total", "serve")
        self._g_queue = self.registry.gauge("serve_queue_depth", "serve")
        self._h_latency = self.registry.histogram("serve_latency_ms", "serve")
        # pipeline lag attribution (obs/pipeline_trace.py naming): how long
        # requests sat queued before the batcher granted them a batch slot —
        # the serving path's analogue of the learner's sample-age lag
        self._h_slot_wait = self.registry.histogram(
            "lag_batch_slot_wait_ms", "serve")
        self._lock = threading.Lock()
        self._lat_ms: collections.deque = collections.deque(maxlen=latency_window)
        self._reset_window()
        # lifetime counters (never reset; stats() reports them)
        self.total_requests = 0
        self.total_shed = 0
        self.total_batches = 0
        self.total_swaps = 0
        self.total_cancelled = 0

    def _reset_window(self) -> None:
        self._win_requests = 0
        self._win_rows_padded = 0
        self._win_batches = 0
        self._win_shed = 0
        self._win_cancelled = 0
        self._win_queue_depth_sum = 0.0

    # ------------------------------------------------------------- recording
    def record_batch(self, n_requests: int, padded: int, queue_depth: int) -> None:
        with self._lock:
            self._win_requests += n_requests
            self._win_rows_padded += padded
            self._win_batches += 1
            self._win_queue_depth_sum += queue_depth
            self.total_requests += n_requests
            self.total_batches += 1
        self._c_requests.inc(n_requests)
        self._c_batches.inc()
        self._c_padded.inc(padded)
        self._g_queue.set(queue_depth)

    def record_queue_wait(self, wait_ms: float) -> None:
        """Mean queued-request wait of one coalesced batch (submit -> batch
        slot), recorded by MicroBatcher.take."""
        self._h_slot_wait.observe(wait_ms)

    def record_latency_ms(self, latency_ms: float) -> None:
        with self._lock:
            self._lat_ms.append(latency_ms)
        self._h_latency.observe(latency_ms)

    def record_shed(self, n: int = 1) -> None:
        with self._lock:
            self._win_shed += n
            self.total_shed += n
        self._c_shed.inc(n)

    def record_cancelled(self, n: int = 1) -> None:
        """Queued futures dropped by the batcher because their client
        cancelled (result() timeout, disconnect) — capacity saved, not an
        error; a climbing rate means clients are giving up faster than the
        server answers."""
        with self._lock:
            self._win_cancelled += n
            self.total_cancelled += n
        self._c_cancelled.inc(n)

    def record_swap(self, **fields: Any) -> None:
        """A completed (or failed) weight swap; always emitted immediately —
        swaps are rare, load-bearing events that must not wait for the next
        periodic row."""
        with self._lock:
            self.total_swaps += 1
        self._c_swaps.inc()
        if self.logger is not None:
            self.logger.log("swap", **fields)

    # ------------------------------------------------------------- reporting
    def _percentiles(self) -> Dict[str, float]:
        if not self._lat_ms:
            return {}
        arr = np.asarray(self._lat_ms, np.float64)
        p50, p95, p99 = np.percentile(arr, [50, 95, 99])
        return {
            "latency_p50_ms": round(float(p50), 3),
            "latency_p95_ms": round(float(p95), 3),
            "latency_p99_ms": round(float(p99), 3),
            "latency_max_ms": round(float(arr.max()), 3),
        }

    def _snapshot_locked(self) -> Dict[str, Any]:
        batches = max(self._win_batches, 1)
        return {
            "requests": self._win_requests,
            "batches": self._win_batches,
            "shed": self._win_shed,
            "cancelled": self._win_cancelled,
            "batch_occupancy_mean": round(self._win_requests / batches, 3),
            # an idle window pays no padding tax (0/0 is NOT "100% padded")
            "pad_fraction": 0.0 if self._win_rows_padded == 0 else round(
                1.0 - self._win_requests / self._win_rows_padded, 4
            ),
            "queue_depth_mean": round(self._win_queue_depth_sum / batches, 2),
            **self._percentiles(),
        }

    def snapshot(self) -> Dict[str, Any]:
        """Current window stats WITHOUT resetting (for stats()/assertions)."""
        with self._lock:
            return self._snapshot_locked()

    def emit(self, **extra: Any) -> Dict[str, Any]:
        """Write one 'serve' JSONL row from the current window, then reset
        the window (latencies keep their rolling deque — percentiles smooth
        over window boundaries instead of jumping).  Snapshot and reset hold
        ONE lock acquisition: an event recorded between them would vanish
        from every window row."""
        with self._lock:
            row = self._snapshot_locked()
            self._reset_window()
        row.update(extra)
        if self.logger is not None:
            self.logger.log("serve", **row)
        return row

    def stats(self) -> Dict[str, Any]:
        """Lifetime counters plus the live window snapshot."""
        return {
            "total_requests": self.total_requests,
            "total_shed": self.total_shed,
            "total_batches": self.total_batches,
            "total_swaps": self.total_swaps,
            "total_cancelled": self.total_cancelled,
            "batch_occupancy_lifetime": round(
                self.total_requests / max(self.total_batches, 1), 3
            ),
            **self.snapshot(),
        }
