"""Batched low-latency policy inference serving (Ape-X's actor fleet turned
client-facing): dynamic micro-batching over bucketed XLA shapes, lane-sharded
inference on the actor mesh, checkpoint-driven weight hot-swap, and a JSONL
metrics surface.  See docs/SERVING.md."""

from rainbow_iqn_apex_tpu.serving.batcher import (
    MicroBatcher,
    ServeFuture,
    ServerClosed,
    ServerOverloaded,
    pick_bucket,
)
from rainbow_iqn_apex_tpu.serving.engine import (
    InferenceEngine,
    fit_buckets,
    parse_buckets,
)
from rainbow_iqn_apex_tpu.serving.metrics import ServeMetrics
from rainbow_iqn_apex_tpu.serving.server import PolicyServer
from rainbow_iqn_apex_tpu.serving.swap import (
    CheckpointWatcher,
    params_template,
    restore_params,
)

__all__ = [
    "CheckpointWatcher",
    "InferenceEngine",
    "MicroBatcher",
    "PolicyServer",
    "ServeFuture",
    "ServeMetrics",
    "ServerClosed",
    "ServerOverloaded",
    "fit_buckets",
    "params_template",
    "parse_buckets",
    "pick_bucket",
    "restore_params",
]
