"""Batched low-latency policy inference serving (Ape-X's actor fleet turned
client-facing): dynamic micro-batching over bucketed XLA shapes, lane-sharded
inference on the actor mesh, checkpoint-driven weight hot-swap, a JSONL
metrics surface, and (serving/fleet/) a front router + autoscaled engine
fleet.  See docs/SERVING.md.

Exports resolve lazily (PEP 562, the parallel/ pattern): engine/server/swap
pull in jax at import time, but batcher/metrics and the whole fleet layer
(router, registry, autoscaler, rollout) are deliberately jax-free so a
router front-end process — which owns no device — can import them without
paying the device-runtime import tax.
"""

from typing import TYPE_CHECKING

_EXPORTS = {
    "MicroBatcher": "rainbow_iqn_apex_tpu.serving.batcher",
    "RequestCancelled": "rainbow_iqn_apex_tpu.serving.batcher",
    "ServeFuture": "rainbow_iqn_apex_tpu.serving.batcher",
    "ServerClosed": "rainbow_iqn_apex_tpu.serving.batcher",
    "ServerOverloaded": "rainbow_iqn_apex_tpu.serving.batcher",
    "pick_bucket": "rainbow_iqn_apex_tpu.serving.batcher",
    "InferenceEngine": "rainbow_iqn_apex_tpu.serving.engine",
    "fit_buckets": "rainbow_iqn_apex_tpu.serving.engine",
    "parse_buckets": "rainbow_iqn_apex_tpu.serving.engine",
    "ServeMetrics": "rainbow_iqn_apex_tpu.serving.metrics",
    "PolicyServer": "rainbow_iqn_apex_tpu.serving.server",
    # cross-host serving plane (serving/net/): jax-free socket transport
    "RemoteEngine": "rainbow_iqn_apex_tpu.serving.net.client",
    "RemoteTransport": "rainbow_iqn_apex_tpu.serving.net.client",
    "RouterGossip": "rainbow_iqn_apex_tpu.serving.net.gossip",
    "TransportServer": "rainbow_iqn_apex_tpu.serving.net.server",
    "CheckpointWatcher": "rainbow_iqn_apex_tpu.serving.swap",
    "params_template": "rainbow_iqn_apex_tpu.serving.swap",
    "restore_params": "rainbow_iqn_apex_tpu.serving.swap",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name: str):
    module = _EXPORTS.get(name)
    if module is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(module), name)


def __dir__():
    return __all__


if TYPE_CHECKING:  # static analyzers see the eager imports
    from rainbow_iqn_apex_tpu.serving.batcher import (  # noqa: F401
        MicroBatcher,
        RequestCancelled,
        ServeFuture,
        ServerClosed,
        ServerOverloaded,
        pick_bucket,
    )
    from rainbow_iqn_apex_tpu.serving.engine import (  # noqa: F401
        InferenceEngine,
        fit_buckets,
        parse_buckets,
    )
    from rainbow_iqn_apex_tpu.serving.metrics import ServeMetrics  # noqa: F401
    from rainbow_iqn_apex_tpu.serving.net.client import (  # noqa: F401
        RemoteEngine,
        RemoteTransport,
    )
    from rainbow_iqn_apex_tpu.serving.net.gossip import RouterGossip  # noqa: F401
    from rainbow_iqn_apex_tpu.serving.net.server import TransportServer  # noqa: F401
    from rainbow_iqn_apex_tpu.serving.server import PolicyServer  # noqa: F401
    from rainbow_iqn_apex_tpu.serving.swap import (  # noqa: F401
        CheckpointWatcher,
        params_template,
        restore_params,
    )
