"""Engine registry: the fleet's membership layer, built on role leases.

PR 4 already solved discovery/eviction for training hosts — lease files
renewed by `HeartbeatWriter`, edges reported once per epoch by
`HeartbeatMonitor` (parallel/elastic.py).  Serving engines reuse exactly that
machinery instead of growing a second membership protocol: every engine runs
a lease with ``role="engine"`` whose payload carries what the router needs to
dispatch — ``{lanes, buckets, weights_version, queue_depth}`` — refreshed on
every renewal via the writer's ``payload_fn`` hook.  The router discovers a
new engine the moment its lease appears fresh and stops routing to it the
moment the lease expires, through the same timeout that declares a training
host dead.

Two halves:

- **`FleetEngine`** (engine side): one `PolicyServer` plus its lease writer.
  ``adopt(params, version)`` is the rollout's entry point — it refuses
  backward versions locally (defence in depth under the fleet controller's
  own monotonicity check) and stamps the adopted version into the lease.
  ``kill()`` is the in-process analog of SIGKILL: heartbeats stop, queued
  requests fail immediately, nothing drains — the shape the soak's mid-load
  engine kill exercises.
- **`EngineRegistry`** (router side): lease scan -> `EngineHandle` map.
  A handle is *routable* only when its lease is fresh AND a transport is
  attached (in-process: the server object itself; a socket adapter slots in
  at the same seam).  A lease without a transport is visible-but-unroutable:
  the obs surface shows the engine exists even before the router can reach
  it.

Deliberately jax-free: the registry/router side of a fleet must be importable
by a front-end process that never touches a device runtime.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from rainbow_iqn_apex_tpu.parallel.elastic import (
    HeartbeatMonitor,
    HeartbeatWriter,
    Lease,
)
from rainbow_iqn_apex_tpu.serving.batcher import ServerOverloaded


class EngineDead(RuntimeError):
    """Raised by a transport whose engine is gone (lease expired / killed)."""


def _params_digest(params: Any) -> Optional[str]:
    """Best-effort sha256 of an adopted fp32 tree (the cross-host rollout's
    bit-exactness witness).  Module-level, not a method: engine fakes
    borrow the adopt methods unbound (tests/test_quantize.py), so the
    digest must not depend on the instance."""
    from rainbow_iqn_apex_tpu.utils.quantize import tree_digest

    try:
        return tree_digest(params)
    except Exception:
        return None  # a digest failure must never fail the adopt itself


class ServerTransport:
    """In-process transport protocol over a `PolicyServer`.

    The router speaks only this surface — ``submit``/``depth``/``alive``/
    ``version``/``lanes`` — so unit tests drive it with fakes and a network
    front-end implements the same five members over a socket.
    ``version()`` is the FLEET weights version (rollout-assigned, monotone),
    not the engine's internal params_version (which also bumps on direct
    load_params pushes outside any rollout).
    """

    def __init__(self, server: Any, lanes: Optional[int] = None):
        self.server = server
        self.lanes = int(lanes if lanes is not None
                         else getattr(server.engine, "n_devices", 1))
        self.buckets: Tuple[int, ...] = tuple(
            getattr(server.engine, "buckets", ()) or ())
        self._fleet_version = 0

    def submit(self, obs) -> Any:
        # try_submit, not submit: a router probe that finds this engine full
        # moves on to the next engine — it is the ROUTER's shed to count
        # (and only if every engine refuses), not this engine's
        fut = self.server.try_submit(obs)
        if fut is None:
            raise ServerOverloaded(
                f"engine queue full ({self.server.cfg.serve_queue_bound})")
        return fut

    def depth(self) -> int:
        return self.server.batcher.depth()

    def alive(self) -> bool:
        worker = getattr(self.server, "_worker", None)
        return worker is not None and worker.is_alive()

    def version(self) -> int:
        return self._fleet_version

    def set_version(self, version: int) -> None:
        self._fleet_version = int(version)


@dataclasses.dataclass
class EngineHandle:
    """One engine as the registry currently sees it."""

    engine_id: int
    transport: Optional[Any] = None  # ServerTransport-protocol object
    lease: Optional[Lease] = None
    alive: bool = True
    # mark_dead() wall-clock stamp: a dispatch OBSERVED this engine dead,
    # which outranks a lease file that merely has not expired yet — only a
    # beat WRITTEN after the observation clears the suspicion (a killed
    # engine's final lease stays fresh for up to the timeout, and an aborted
    # queue reads depth 0, so a resurrected corpse would rank FIRST)
    suspect_since: Optional[float] = None
    # True when the suspicion came from a TRANSPORT probe (serving/net):
    # the engine process may be alive and beating while its serve plane is
    # wedged, so heartbeats must NOT rehabilitate it — only a later
    # successful probe does.  mark_dead suspicion stays beat-clearable.
    suspect_probe: bool = False

    @property
    def routable(self) -> bool:
        return self.alive and self.transport is not None

    @property
    def lanes(self) -> int:
        if self.transport is not None:
            return max(int(self.transport.lanes), 1)
        return max(int(self.lease.lanes), 1) if self.lease else 1

    def depth(self) -> int:
        """Queue depth: live from the transport when attached, else the
        lease's last renewal (stale by at most one lease interval)."""
        if self.transport is not None:
            try:
                return int(self.transport.depth())
            except Exception:
                return 1 << 30  # an unreadable depth routes LAST, not first
        return max(int(self.lease.queue_depth), 0) if self.lease else 0

    def version(self) -> int:
        if self.transport is not None:
            return int(self.transport.version())
        return int(self.lease.weight_version) if self.lease else -1


class FleetEngine:
    """Engine-side composition: PolicyServer + self-registering lease.

    ``engine_id`` doubles as the lease file's host id, so one heartbeat
    directory holds training hosts and serving engines side by side,
    distinguished by the lease's ``role`` field.
    """

    def __init__(self, server: Any, engine_id: int, heartbeat_dir: str,
                 interval_s: float = 0.5, epoch: int = 0,
                 lanes: Optional[int] = None):
        self.server = server
        self.engine_id = int(engine_id)
        self.transport = ServerTransport(server, lanes=lanes)
        self.writer = HeartbeatWriter(
            heartbeat_dir, engine_id, interval_s, role="engine", epoch=epoch,
            payload_fn=self._lease_payload,
        )
        self.writer.update_payload(
            lanes=self.transport.lanes, buckets=list(self.transport.buckets))
        # the cross-host rollout's bit-exactness witness: sha256 of the
        # fp32 params this engine currently serves (TransportServer
        # piggybacks it on pongs; net_smoke gates on it).  Computed LAZILY
        # on first `served_digest` read per adopted version — an in-process
        # fleet with no TransportServer never reads it and pays nothing
        # (hashing a real-size tree per engine per publish is not free)
        self._served_params: Optional[Any] = None
        self._served_digest: Optional[str] = None

    def _lease_payload(self) -> Dict[str, Any]:
        return {
            "weight_version": self.transport.version(),
            "queue_depth": self.transport.depth(),
        }

    # -------------------------------------------------------------- lifecycle
    def start(self, warmup: bool = True) -> "FleetEngine":
        self.server.start(warmup=warmup)
        self.writer.start()
        return self

    def stop(self) -> None:
        """Graceful decommission: lease first (the router stops routing new
        requests at the next expiry), then drain what's queued."""
        self.writer.stop()
        self.server.stop(drain=True)

    def kill(self) -> None:
        """The in-process SIGKILL: heartbeats stop cold and every queued
        request fails NOW — the lease then expires on the monitor's clock,
        exactly like a real dead process.  What the soak's mid-load engine
        kill and the re-route invariant test exercise."""
        self.writer.stop()
        self.server.stop(drain=False)

    def proc(self) -> "_EngineProc":
        """Process-like view for `RoleSupervisor`/`Autoscaler` supervision:
        ``poll()`` reports this in-process engine dead once its serve worker
        is gone, ``kill()`` is the hard stop."""
        return _EngineProc(self)

    # ---------------------------------------------------------------- rollout
    def adopt(self, params: Any, version: int) -> int:
        """Adopt rollout ``version``; refuses backward versions (the engine-
        local mirror of CheckpointWatcher's older_than_loaded refusal, so a
        confused controller cannot regress THIS engine even if the fleet
        check is bypassed)."""
        version = int(version)
        if version <= self.transport.version() and self.transport.version() > 0:
            raise ValueError(
                f"engine {self.engine_id}: refusing backward/duplicate weight "
                f"rollout {version} (serving {self.transport.version()})"
            )
        self.server.load_params(params)
        self.transport.set_version(version)
        self.writer.set_weight_version(version)
        self._served_params, self._served_digest = params, None
        return version

    @property
    def served_digest(self) -> Optional[str]:
        if self._served_digest is None and self._served_params is not None:
            self._served_digest = _params_digest(self._served_params)
        return self._served_digest

    # delta-compressed rollout (utils/quantize.py; FleetRollout
    # compression="int8_delta"): the engine holds a DeltaDecoder whose
    # reconstruction is bit-exact with the controller's encoder, so N
    # engines adopting the same packet stream all serve identical weights
    def _packet_decoder(self):
        if not hasattr(self, "_decoder"):
            from rainbow_iqn_apex_tpu.utils.quantize import DeltaDecoder

            self._decoder = DeltaDecoder()
        return self._decoder

    def adopt_packet(self, packet: Any) -> int:
        """Adopt one delta/base packet.  Backward/duplicate packets are
        refused (ValueError, same contract as `adopt`); a chain gap raises
        `DeltaChainBroken` — the rollout counts the adopt failed and
        ``sync()`` repairs it with the chain-from-base."""
        version = int(packet.version)
        if version <= self.transport.version() and self.transport.version() > 0:
            raise ValueError(
                f"engine {self.engine_id}: refusing backward/duplicate weight "
                f"rollout {version} (serving {self.transport.version()})"
            )
        params = self._packet_decoder().apply(packet)
        self.server.load_params(params)
        self.transport.set_version(version)
        self.writer.set_weight_version(version)
        self._served_params, self._served_digest = params, None
        return version

    def adopt_chain(self, packets: Any) -> int:
        """Catch up through a chain-from-base (late join, missed packets).
        Idempotent: packets at or below the held version are skipped.  The
        reload fires whenever the SERVED version trails the decoder — not
        only when the chain advanced the decoder: a prior adopt whose
        decode succeeded but whose ``load_params`` failed (dying engine,
        mid-kill race) leaves the decoder ahead of the transport, and this
        is sync()'s one retry path for that engine — skipping the reload
        there would fence it out of routing forever."""
        decoder = self._packet_decoder()
        params = decoder.apply_chain(list(packets))
        if decoder.version > self.transport.version():
            self.server.load_params(params)
            self.transport.set_version(decoder.version)
            self.writer.set_weight_version(decoder.version)
            self._served_params, self._served_digest = params, None
        return decoder.version


class _EngineProc:
    """Adapter making an in-process `FleetEngine` look like a subprocess to
    the supervision layer (poll() -> rc or None, kill())."""

    def __init__(self, engine: FleetEngine):
        self.engine = engine

    def poll(self) -> Optional[int]:
        return None if self.engine.transport.alive() else 1

    def kill(self) -> None:
        self.engine.kill()


class EngineRegistry:
    """Lease-driven engine membership for the router.

    ``poll()`` refreshes the lease view and returns the edge events since the
    last call (``engine_alive`` / ``engine_dead``, once per lease epoch —
    `HeartbeatMonitor.poll` semantics).  Without a heartbeat directory
    (pure in-process fleets, unit tests) liveness falls back to the
    transport's own ``alive()``.
    """

    def __init__(self, heartbeat_dir: Optional[str] = None,
                 lease_timeout_s: float = 3.0,
                 logger=None, obs_registry=None,
                 transport_factory=None,
                 probe_timeout_s: float = 0.5,
                 probe_interval_s: float = 1.0,
                 net_stats_interval_s: float = 5.0):
        self.monitor = (
            HeartbeatMonitor(heartbeat_dir, timeout_s=lease_timeout_s)
            if heartbeat_dir else None
        )
        self.logger = logger
        self.obs_registry = obs_registry
        # cross-host discovery (serving/net/): when a factory is given, an
        # engine lease advertising addr:port gets a remote transport built
        # from it — `lease -> transport` is the whole discovery story, no
        # second protocol.  None (default) keeps the registry lease-only:
        # remote leases stay visible-but-unroutable, bitwise the old path.
        self.transport_factory = transport_factory
        # transport-liveness probes are BOUNDED per probe: a hung remote
        # (SYN-accepted, wedged engine) costs the sweep at most
        # probe_timeout_s, never a stall — and only every probe_interval_s
        self.probe_timeout_s = float(probe_timeout_s)
        self.probe_interval_s = float(probe_interval_s)
        self.net_stats_interval_s = float(net_stats_interval_s)
        self._t_probe: Dict[int, float] = {}
        self._t_net_stats = 0.0
        self._lock = threading.Lock()
        self._handles: Dict[int, EngineHandle] = {}

    # ------------------------------------------------------------ membership
    def attach(self, engine_id: int, transport: Any) -> EngineHandle:
        """Register a dispatchable transport for ``engine_id`` (in-process:
        pass a `ServerTransport` or a `FleetEngine.transport`)."""
        with self._lock:
            handle = self._handles.get(int(engine_id))
            if handle is None:
                handle = EngineHandle(engine_id=int(engine_id))
                self._handles[int(engine_id)] = handle
            handle.transport = transport
            handle.alive = True
            handle.suspect_since = None  # a fresh transport is a new start
            handle.suspect_probe = False
        self._observe()
        return handle

    def detach(self, engine_id: int) -> None:
        with self._lock:
            self._handles.pop(int(engine_id), None)
        self._observe()

    def handles(self) -> List[EngineHandle]:
        with self._lock:
            return list(self._handles.values())

    def get(self, engine_id: int) -> Optional[EngineHandle]:
        with self._lock:
            return self._handles.get(int(engine_id))

    def routable(self) -> List[EngineHandle]:
        with self._lock:
            return [h for h in self._handles.values() if h.routable]

    def revivable(self) -> List[EngineHandle]:
        """Handles that are NOT routable right now but whose engine process
        still looks alive: a fresh lease behind a mark_dead suspicion
        (monitor mode) or a live transport (attach mode).  That is a wire
        flap, not an engine death — the next written beat (or successful
        probe) rehabilitates the handle, so a re-route should PARK for
        these instead of declaring the accepted request lost.  The lease
        view is the last poll()'s snapshot, so a truly dead engine can
        linger here for one lease timeout — the router's reroute window
        bounds how long anyone waits on it."""
        with self._lock:
            out = []
            for h in self._handles.values():
                if h.routable or h.transport is None:
                    continue
                if h.lease is not None:
                    if h.lease.fresh:
                        out.append(h)
                elif h.transport.alive():
                    out.append(h)
            return out

    # ------------------------------------------------------------------ poll
    def poll(self) -> List[Dict[str, Any]]:
        """One membership sweep; returns the edge events it emitted."""
        events: List[Dict[str, Any]] = []
        if self.monitor is not None:
            newly_dead, newly_alive = self.monitor.poll()
            leases = self.monitor.leases()
            now = time.time()
            with self._lock:
                for hid, lease in leases.items():
                    if lease.role != "engine":
                        continue  # training hosts share the directory
                    handle = self._handles.get(hid)
                    if handle is None:
                        # discovered via lease only: visible, unroutable
                        # until a transport attaches (the socket seam).
                        # The monitor only edges on REVIVALS, so first
                        # discovery of a fresh lease is the registry's own
                        # alive edge to report.
                        handle = EngineHandle(engine_id=hid, transport=None)
                        self._handles[hid] = handle
                        if lease.fresh:
                            events.append({"event": "engine_alive",
                                           "engine": hid,
                                           "epoch": lease.epoch})
                    if (lease.fresh and lease.addr and lease.port
                            and self.transport_factory is not None):
                        # cross-host discovery: the lease advertises where
                        # the engine's TransportServer listens; the factory
                        # returns a LAZY client (no dial here — the first
                        # probe/submit connects, bounded).  A FRESH lease
                        # advertising a NEW endpoint (supervisor respawned
                        # the host on another ephemeral port) REPLACES the
                        # old remote transport: keeping it would dial the
                        # dead port forever, and probe suspicion — which
                        # only a successful probe clears — would fence the
                        # healthy respawn out permanently.
                        old = handle.transport
                        endpoint_moved = (
                            old is not None
                            and hasattr(old, "host") and hasattr(old, "port")
                            and (old.host, old.port) != (lease.addr,
                                                         lease.port))
                        if handle.transport is None or endpoint_moved:
                            try:
                                handle.transport = self.transport_factory(
                                    lease)
                            except Exception:
                                pass  # mis-advertised lease: unroutable
                            else:
                                handle.suspect_since = None  # new endpoint
                                handle.suspect_probe = False  # = new start
                                if endpoint_moved and hasattr(old, "close"):
                                    try:
                                        old.close()
                                    except Exception:
                                        pass
                    handle.lease = lease
                    if (handle.suspect_since is not None
                            and not handle.suspect_probe):
                        # only a beat WRITTEN after the mark_dead observation
                        # rehabilitates the engine — the stale-but-fresh
                        # final lease of a killed process does not.  PROBE
                        # suspicion is exempt entirely: a wedged serve plane
                        # keeps beating, so only a good probe clears it.
                        if now - lease.age_s > handle.suspect_since:
                            handle.suspect_since = None
                    handle.alive = (lease.fresh
                                    and handle.suspect_since is None)
                for lease in newly_dead:
                    if lease.role == "engine":
                        events.append({"event": "engine_dead",
                                       "engine": lease.host,
                                       "epoch": lease.epoch})
                for lease in newly_alive:
                    if lease.role == "engine":
                        events.append({"event": "engine_alive",
                                       "engine": lease.host,
                                       "epoch": lease.epoch})
        else:
            with self._lock:
                for handle in self._handles.values():
                    was = handle.alive
                    now = (handle.transport is not None
                           and handle.transport.alive())
                    handle.alive = now
                    if was and not now:
                        events.append({"event": "engine_dead",
                                       "engine": handle.engine_id})
                    elif now and not was:
                        events.append({"event": "engine_alive",
                                       "engine": handle.engine_id})
        self._probe_remotes()
        self._emit_net_stats()
        if self.logger is not None:
            for ev in events:
                self.logger.log("fault", **ev)
        self._observe()
        return events

    def _probe_remotes(self) -> None:
        """Transport-liveness sweep over remote transports: each probe is
        bounded at ``probe_timeout_s`` (a hung remote can never stall
        discovery/eviction), rate-limited to ``probe_interval_s`` per
        engine.  A failed probe marks the engine suspect exactly like
        ``mark_dead`` — only a lease beat written AFTER the observation (or
        a later successful probe) rehabilitates it."""
        now = time.time()
        with self._lock:
            due = [h for h in self._handles.values()
                   if h.transport is not None
                   and hasattr(h.transport, "probe")
                   and (h.lease is None or h.lease.fresh)
                   and now - self._t_probe.get(h.engine_id, 0.0)
                   >= self.probe_interval_s]
        def probe_one(handle: EngineHandle) -> None:
            rtt = handle.transport.probe(timeout_s=self.probe_timeout_s)
            with self._lock:
                if rtt is None:
                    handle.alive = False
                    handle.suspect_since = time.time()
                    handle.suspect_probe = True
                else:
                    handle.suspect_since = None
                    handle.suspect_probe = False
                    handle.alive = (handle.lease is None
                                    or handle.lease.fresh)
            if rtt is not None and self.obs_registry is not None:
                self.obs_registry.gauge(
                    f"net_rtt_ms_engine{handle.engine_id}", "net").set(rtt)

        # probes for DISTINCT engines run concurrently: serial probing
        # would stall the sweep M x timeout during a rack outage — exactly
        # when fast eviction/re-route matters most.  Each probe is bounded,
        # so the whole fan-out is ~one probe_timeout_s.
        threads = []
        for handle in due:
            self._t_probe[handle.engine_id] = now
            if len(due) == 1:
                probe_one(handle)
            else:
                t = threading.Thread(target=probe_one, args=(handle,),
                                     name="net-probe", daemon=True)
                t.start()
                threads.append(t)
        for t in threads:
            t.join(timeout=self.probe_timeout_s + 1.0)

    def _emit_net_stats(self) -> None:
        """One periodic `net` stats row per remote transport (per-peer
        rtt/reconnects/bytes — obs_report's ``net:`` section input)."""
        if self.logger is None or self.net_stats_interval_s <= 0:
            return
        now = time.time()
        if now - self._t_net_stats < self.net_stats_interval_s:
            return
        self._t_net_stats = now
        with self._lock:
            transports = [h.transport for h in self._handles.values()
                          if h.transport is not None
                          and hasattr(h.transport, "stats")]
        for transport in transports:
            try:
                self.logger.log("net", event="stats", **transport.stats())
            except Exception:
                pass

    def mark_dead(self, engine_id: int) -> None:
        """Immediate eviction (a dispatch observed the engine dead) — faster
        than waiting out the lease timeout.  Sticky against the engine's
        LAST lease file (which stays fresh up to the timeout): only a beat
        written after this observation, or a new transport attach, revives
        the engine."""
        with self._lock:
            handle = self._handles.get(int(engine_id))
            if handle is not None:
                handle.alive = False
                handle.suspect_since = time.time()
                handle.suspect_probe = False  # death suspicion: a beat
                # written after the observation DOES rehabilitate
        self._observe()

    # ----------------------------------------------------------------- stats
    def _observe(self) -> None:
        if self.obs_registry is None:
            return
        with self._lock:
            handles = list(self._handles.values())
        self.obs_registry.gauge("fleet_engines", "router").set(len(handles))
        self.obs_registry.gauge("fleet_engines_routable", "router").set(
            sum(1 for h in handles if h.routable))

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        """Per-engine {depth, version, alive, lanes} — the route row's
        ``engines`` field and obs_report's depth/version spread."""
        out: Dict[str, Dict[str, Any]] = {}
        for h in self.handles():
            out[str(h.engine_id)] = {
                "depth": h.depth() if h.routable else None,
                "version": h.version(),
                "alive": bool(h.alive),
                "lanes": h.lanes,
            }
        return out
