"""Engine registry: the fleet's membership layer, built on role leases.

PR 4 already solved discovery/eviction for training hosts — lease files
renewed by `HeartbeatWriter`, edges reported once per epoch by
`HeartbeatMonitor` (parallel/elastic.py).  Serving engines reuse exactly that
machinery instead of growing a second membership protocol: every engine runs
a lease with ``role="engine"`` whose payload carries what the router needs to
dispatch — ``{lanes, buckets, weights_version, queue_depth}`` — refreshed on
every renewal via the writer's ``payload_fn`` hook.  The router discovers a
new engine the moment its lease appears fresh and stops routing to it the
moment the lease expires, through the same timeout that declares a training
host dead.

Two halves:

- **`FleetEngine`** (engine side): one `PolicyServer` plus its lease writer.
  ``adopt(params, version)`` is the rollout's entry point — it refuses
  backward versions locally (defence in depth under the fleet controller's
  own monotonicity check) and stamps the adopted version into the lease.
  ``kill()`` is the in-process analog of SIGKILL: heartbeats stop, queued
  requests fail immediately, nothing drains — the shape the soak's mid-load
  engine kill exercises.
- **`EngineRegistry`** (router side): lease scan -> `EngineHandle` map.
  A handle is *routable* only when its lease is fresh AND a transport is
  attached (in-process: the server object itself; a socket adapter slots in
  at the same seam).  A lease without a transport is visible-but-unroutable:
  the obs surface shows the engine exists even before the router can reach
  it.

Deliberately jax-free: the registry/router side of a fleet must be importable
by a front-end process that never touches a device runtime.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from rainbow_iqn_apex_tpu.parallel.elastic import (
    HeartbeatMonitor,
    HeartbeatWriter,
    Lease,
)
from rainbow_iqn_apex_tpu.serving.batcher import ServerOverloaded


class EngineDead(RuntimeError):
    """Raised by a transport whose engine is gone (lease expired / killed)."""


class ServerTransport:
    """In-process transport protocol over a `PolicyServer`.

    The router speaks only this surface — ``submit``/``depth``/``alive``/
    ``version``/``lanes`` — so unit tests drive it with fakes and a network
    front-end implements the same five members over a socket.
    ``version()`` is the FLEET weights version (rollout-assigned, monotone),
    not the engine's internal params_version (which also bumps on direct
    load_params pushes outside any rollout).
    """

    def __init__(self, server: Any, lanes: Optional[int] = None):
        self.server = server
        self.lanes = int(lanes if lanes is not None
                         else getattr(server.engine, "n_devices", 1))
        self.buckets: Tuple[int, ...] = tuple(
            getattr(server.engine, "buckets", ()) or ())
        self._fleet_version = 0

    def submit(self, obs) -> Any:
        # try_submit, not submit: a router probe that finds this engine full
        # moves on to the next engine — it is the ROUTER's shed to count
        # (and only if every engine refuses), not this engine's
        fut = self.server.try_submit(obs)
        if fut is None:
            raise ServerOverloaded(
                f"engine queue full ({self.server.cfg.serve_queue_bound})")
        return fut

    def depth(self) -> int:
        return self.server.batcher.depth()

    def alive(self) -> bool:
        worker = getattr(self.server, "_worker", None)
        return worker is not None and worker.is_alive()

    def version(self) -> int:
        return self._fleet_version

    def set_version(self, version: int) -> None:
        self._fleet_version = int(version)


@dataclasses.dataclass
class EngineHandle:
    """One engine as the registry currently sees it."""

    engine_id: int
    transport: Optional[Any] = None  # ServerTransport-protocol object
    lease: Optional[Lease] = None
    alive: bool = True
    # mark_dead() wall-clock stamp: a dispatch OBSERVED this engine dead,
    # which outranks a lease file that merely has not expired yet — only a
    # beat WRITTEN after the observation clears the suspicion (a killed
    # engine's final lease stays fresh for up to the timeout, and an aborted
    # queue reads depth 0, so a resurrected corpse would rank FIRST)
    suspect_since: Optional[float] = None

    @property
    def routable(self) -> bool:
        return self.alive and self.transport is not None

    @property
    def lanes(self) -> int:
        if self.transport is not None:
            return max(int(self.transport.lanes), 1)
        return max(int(self.lease.lanes), 1) if self.lease else 1

    def depth(self) -> int:
        """Queue depth: live from the transport when attached, else the
        lease's last renewal (stale by at most one lease interval)."""
        if self.transport is not None:
            try:
                return int(self.transport.depth())
            except Exception:
                return 1 << 30  # an unreadable depth routes LAST, not first
        return max(int(self.lease.queue_depth), 0) if self.lease else 0

    def version(self) -> int:
        if self.transport is not None:
            return int(self.transport.version())
        return int(self.lease.weight_version) if self.lease else -1


class FleetEngine:
    """Engine-side composition: PolicyServer + self-registering lease.

    ``engine_id`` doubles as the lease file's host id, so one heartbeat
    directory holds training hosts and serving engines side by side,
    distinguished by the lease's ``role`` field.
    """

    def __init__(self, server: Any, engine_id: int, heartbeat_dir: str,
                 interval_s: float = 0.5, epoch: int = 0,
                 lanes: Optional[int] = None):
        self.server = server
        self.engine_id = int(engine_id)
        self.transport = ServerTransport(server, lanes=lanes)
        self.writer = HeartbeatWriter(
            heartbeat_dir, engine_id, interval_s, role="engine", epoch=epoch,
            payload_fn=self._lease_payload,
        )
        self.writer.update_payload(
            lanes=self.transport.lanes, buckets=list(self.transport.buckets))

    def _lease_payload(self) -> Dict[str, Any]:
        return {
            "weight_version": self.transport.version(),
            "queue_depth": self.transport.depth(),
        }

    # -------------------------------------------------------------- lifecycle
    def start(self, warmup: bool = True) -> "FleetEngine":
        self.server.start(warmup=warmup)
        self.writer.start()
        return self

    def stop(self) -> None:
        """Graceful decommission: lease first (the router stops routing new
        requests at the next expiry), then drain what's queued."""
        self.writer.stop()
        self.server.stop(drain=True)

    def kill(self) -> None:
        """The in-process SIGKILL: heartbeats stop cold and every queued
        request fails NOW — the lease then expires on the monitor's clock,
        exactly like a real dead process.  What the soak's mid-load engine
        kill and the re-route invariant test exercise."""
        self.writer.stop()
        self.server.stop(drain=False)

    def proc(self) -> "_EngineProc":
        """Process-like view for `RoleSupervisor`/`Autoscaler` supervision:
        ``poll()`` reports this in-process engine dead once its serve worker
        is gone, ``kill()`` is the hard stop."""
        return _EngineProc(self)

    # ---------------------------------------------------------------- rollout
    def adopt(self, params: Any, version: int) -> int:
        """Adopt rollout ``version``; refuses backward versions (the engine-
        local mirror of CheckpointWatcher's older_than_loaded refusal, so a
        confused controller cannot regress THIS engine even if the fleet
        check is bypassed)."""
        version = int(version)
        if version <= self.transport.version() and self.transport.version() > 0:
            raise ValueError(
                f"engine {self.engine_id}: refusing backward/duplicate weight "
                f"rollout {version} (serving {self.transport.version()})"
            )
        self.server.load_params(params)
        self.transport.set_version(version)
        self.writer.set_weight_version(version)
        return version

    # delta-compressed rollout (utils/quantize.py; FleetRollout
    # compression="int8_delta"): the engine holds a DeltaDecoder whose
    # reconstruction is bit-exact with the controller's encoder, so N
    # engines adopting the same packet stream all serve identical weights
    def _packet_decoder(self):
        if not hasattr(self, "_decoder"):
            from rainbow_iqn_apex_tpu.utils.quantize import DeltaDecoder

            self._decoder = DeltaDecoder()
        return self._decoder

    def adopt_packet(self, packet: Any) -> int:
        """Adopt one delta/base packet.  Backward/duplicate packets are
        refused (ValueError, same contract as `adopt`); a chain gap raises
        `DeltaChainBroken` — the rollout counts the adopt failed and
        ``sync()`` repairs it with the chain-from-base."""
        version = int(packet.version)
        if version <= self.transport.version() and self.transport.version() > 0:
            raise ValueError(
                f"engine {self.engine_id}: refusing backward/duplicate weight "
                f"rollout {version} (serving {self.transport.version()})"
            )
        params = self._packet_decoder().apply(packet)
        self.server.load_params(params)
        self.transport.set_version(version)
        self.writer.set_weight_version(version)
        return version

    def adopt_chain(self, packets: Any) -> int:
        """Catch up through a chain-from-base (late join, missed packets).
        Idempotent: packets at or below the held version are skipped.  The
        reload fires whenever the SERVED version trails the decoder — not
        only when the chain advanced the decoder: a prior adopt whose
        decode succeeded but whose ``load_params`` failed (dying engine,
        mid-kill race) leaves the decoder ahead of the transport, and this
        is sync()'s one retry path for that engine — skipping the reload
        there would fence it out of routing forever."""
        decoder = self._packet_decoder()
        params = decoder.apply_chain(list(packets))
        if decoder.version > self.transport.version():
            self.server.load_params(params)
            self.transport.set_version(decoder.version)
            self.writer.set_weight_version(decoder.version)
        return decoder.version


class _EngineProc:
    """Adapter making an in-process `FleetEngine` look like a subprocess to
    the supervision layer (poll() -> rc or None, kill())."""

    def __init__(self, engine: FleetEngine):
        self.engine = engine

    def poll(self) -> Optional[int]:
        return None if self.engine.transport.alive() else 1

    def kill(self) -> None:
        self.engine.kill()


class EngineRegistry:
    """Lease-driven engine membership for the router.

    ``poll()`` refreshes the lease view and returns the edge events since the
    last call (``engine_alive`` / ``engine_dead``, once per lease epoch —
    `HeartbeatMonitor.poll` semantics).  Without a heartbeat directory
    (pure in-process fleets, unit tests) liveness falls back to the
    transport's own ``alive()``.
    """

    def __init__(self, heartbeat_dir: Optional[str] = None,
                 lease_timeout_s: float = 3.0,
                 logger=None, obs_registry=None):
        self.monitor = (
            HeartbeatMonitor(heartbeat_dir, timeout_s=lease_timeout_s)
            if heartbeat_dir else None
        )
        self.logger = logger
        self.obs_registry = obs_registry
        self._lock = threading.Lock()
        self._handles: Dict[int, EngineHandle] = {}

    # ------------------------------------------------------------ membership
    def attach(self, engine_id: int, transport: Any) -> EngineHandle:
        """Register a dispatchable transport for ``engine_id`` (in-process:
        pass a `ServerTransport` or a `FleetEngine.transport`)."""
        with self._lock:
            handle = self._handles.get(int(engine_id))
            if handle is None:
                handle = EngineHandle(engine_id=int(engine_id))
                self._handles[int(engine_id)] = handle
            handle.transport = transport
            handle.alive = True
            handle.suspect_since = None  # a fresh transport is a new start
        self._observe()
        return handle

    def detach(self, engine_id: int) -> None:
        with self._lock:
            self._handles.pop(int(engine_id), None)
        self._observe()

    def handles(self) -> List[EngineHandle]:
        with self._lock:
            return list(self._handles.values())

    def get(self, engine_id: int) -> Optional[EngineHandle]:
        with self._lock:
            return self._handles.get(int(engine_id))

    def routable(self) -> List[EngineHandle]:
        with self._lock:
            return [h for h in self._handles.values() if h.routable]

    # ------------------------------------------------------------------ poll
    def poll(self) -> List[Dict[str, Any]]:
        """One membership sweep; returns the edge events it emitted."""
        events: List[Dict[str, Any]] = []
        if self.monitor is not None:
            newly_dead, newly_alive = self.monitor.poll()
            leases = self.monitor.leases()
            now = time.time()
            with self._lock:
                for hid, lease in leases.items():
                    if lease.role != "engine":
                        continue  # training hosts share the directory
                    handle = self._handles.get(hid)
                    if handle is None:
                        # discovered via lease only: visible, unroutable
                        # until a transport attaches (the socket seam).
                        # The monitor only edges on REVIVALS, so first
                        # discovery of a fresh lease is the registry's own
                        # alive edge to report.
                        handle = EngineHandle(engine_id=hid, transport=None)
                        self._handles[hid] = handle
                        if lease.fresh:
                            events.append({"event": "engine_alive",
                                           "engine": hid,
                                           "epoch": lease.epoch})
                    handle.lease = lease
                    if handle.suspect_since is not None:
                        # only a beat WRITTEN after the mark_dead observation
                        # rehabilitates the engine — the stale-but-fresh
                        # final lease of a killed process does not
                        if now - lease.age_s > handle.suspect_since:
                            handle.suspect_since = None
                    handle.alive = (lease.fresh
                                    and handle.suspect_since is None)
                for lease in newly_dead:
                    if lease.role == "engine":
                        events.append({"event": "engine_dead",
                                       "engine": lease.host,
                                       "epoch": lease.epoch})
                for lease in newly_alive:
                    if lease.role == "engine":
                        events.append({"event": "engine_alive",
                                       "engine": lease.host,
                                       "epoch": lease.epoch})
        else:
            with self._lock:
                for handle in self._handles.values():
                    was = handle.alive
                    now = (handle.transport is not None
                           and handle.transport.alive())
                    handle.alive = now
                    if was and not now:
                        events.append({"event": "engine_dead",
                                       "engine": handle.engine_id})
                    elif now and not was:
                        events.append({"event": "engine_alive",
                                       "engine": handle.engine_id})
        if self.logger is not None:
            for ev in events:
                self.logger.log("fault", **ev)
        self._observe()
        return events

    def mark_dead(self, engine_id: int) -> None:
        """Immediate eviction (a dispatch observed the engine dead) — faster
        than waiting out the lease timeout.  Sticky against the engine's
        LAST lease file (which stays fresh up to the timeout): only a beat
        written after this observation, or a new transport attach, revives
        the engine."""
        with self._lock:
            handle = self._handles.get(int(engine_id))
            if handle is not None:
                handle.alive = False
                handle.suspect_since = time.time()
        self._observe()

    # ----------------------------------------------------------------- stats
    def _observe(self) -> None:
        if self.obs_registry is None:
            return
        with self._lock:
            handles = list(self._handles.values())
        self.obs_registry.gauge("fleet_engines", "router").set(len(handles))
        self.obs_registry.gauge("fleet_engines_routable", "router").set(
            sum(1 for h in handles if h.routable))

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        """Per-engine {depth, version, alive, lanes} — the route row's
        ``engines`` field and obs_report's depth/version spread."""
        out: Dict[str, Dict[str, Any]] = {}
        for h in self.handles():
            out[str(h.engine_id)] = {
                "depth": h.depth() if h.routable else None,
                "version": h.version(),
                "alive": bool(h.alive),
                "lanes": h.lanes,
            }
        return out
