"""Autoscaler: grow/shrink the engine fleet on load, without flapping.

Inputs are the gauges the serving tier already publishes — mean per-engine
queue fill (the router's lease view) and completion p99 — not a new metrics
path.  The control law is deliberately boring:

- **hysteresis**: a scale decision needs ``patience`` CONSECUTIVE breached
  evaluations, and the out/in thresholds are separated (up at 75% fill,
  down at 20% by default), so load oscillating around one threshold cannot
  flap the fleet (tier-1 asserted in tests/test_fleet.py);
- **cooldown**: after any action the scaler holds for ``cooldown_s`` — an
  engine that just spawned needs a warmup's worth of wall clock before its
  effect on depth is measurable, and judging mid-warmup double-scales;
- **bounds**: the engine count stays in [min_engines, max_engines].

Engine processes live under the PR-4 `RoleSupervisor`: a CRASHED engine is
respawned with the shared backoff schedule (and eventually evicted on budget
exhaustion) exactly like a dead actor host, while a deliberately
decommissioned one is ``release``d first so its exit can never read as a
failure.  Every decision is emitted as a ``scale`` row.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, List, Optional

from rainbow_iqn_apex_tpu.parallel.elastic import RoleSupervisor


@dataclasses.dataclass(frozen=True)
class ScalePolicy:
    """The autoscaler's knobs (Config.fleet_scale_* fields)."""

    min_engines: int = 1
    max_engines: int = 4
    up_depth: float = 0.75  # mean queue fill fraction that argues scale-OUT
    down_depth: float = 0.2  # ... and scale-IN
    p99_ms: float = 0.0  # p99 latency scale-out trigger; 0 = depth only
    patience: int = 3  # consecutive breached evaluations before acting
    cooldown_s: float = 10.0  # hold after any action

    @classmethod
    def from_config(cls, cfg) -> "ScalePolicy":
        return cls(
            min_engines=cfg.fleet_min_engines,
            max_engines=cfg.fleet_max_engines,
            up_depth=cfg.fleet_scale_up_depth,
            down_depth=cfg.fleet_scale_down_depth,
            p99_ms=cfg.fleet_scale_p99_ms,
            patience=cfg.fleet_scale_patience,
            cooldown_s=cfg.fleet_scale_cooldown_s,
        )


class Autoscaler:
    """Hysteretic engine-count controller.

    ``spawn_engine(engine_id, epoch)`` must start a new engine and return a
    process-like object (``poll()`` -> rc or None, ``kill()``) the
    supervisor can watch; ``stop_engine(engine_id)`` decommissions one
    (graceful: lease first, then drain).  ``load_fn()`` returns
    ``{"engines": n_routable, "depth_frac": mean fill 0..1, "p99_ms": x|None}``
    — `FrontRouter.mean_depth_fraction`/`p99_ms` in the real wiring, a
    scripted sequence in the hysteresis tests.
    """

    def __init__(
        self,
        policy: ScalePolicy,
        spawn_engine: Callable[[int, int], Any],
        stop_engine: Callable[[int], None],
        load_fn: Callable[[], Dict[str, Any]],
        supervisor: Optional[RoleSupervisor] = None,
        logger=None,
        obs_registry=None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.policy = policy
        self.spawn_engine = spawn_engine
        self.stop_engine = stop_engine
        self.load_fn = load_fn
        self.supervisor = supervisor
        self.logger = logger
        self.obs_registry = obs_registry
        self.clock = clock
        self._engine_ids: List[int] = []
        self._next_id = 0
        self._breach_up = 0
        self._breach_down = 0
        self._t_last_action = -float("inf")
        self.actions: List[Dict[str, Any]] = []  # lifetime decision log

    # ------------------------------------------------------------- membership
    def adopt_engine(self, engine_id: int, proc: Any = None) -> None:
        """Track an engine the harness already started (the initial fleet);
        registered with the supervisor so a crash respawns it like any
        scaled-out engine."""
        self._engine_ids.append(int(engine_id))
        self._next_id = max(self._next_id, int(engine_id) + 1)
        if self.supervisor is not None:
            self.supervisor.register(
                f"engine{engine_id}",
                lambda epoch, eid=int(engine_id): self.spawn_engine(eid, epoch),
                proc=proc if proc is not None else _AliveProc(),
                meta={"engine": int(engine_id)},
            )

    def engines(self) -> List[int]:
        return list(self._engine_ids)

    # --------------------------------------------------------------- decision
    def _emit(self, action: str, reason: str, load: Dict[str, Any],
              engine_id: int) -> Dict[str, Any]:
        row = {
            "action": action,
            "engines": len(self._engine_ids),
            "engine": engine_id,
            "reason": reason,
            "depth_frac": round(float(load.get("depth_frac", 0.0)), 4),
            "p99_ms": load.get("p99_ms"),
        }
        self.actions.append(row)
        if self.logger is not None:
            self.logger.log("scale", **row)
        if self.obs_registry is not None:
            self.obs_registry.counter(f"scale_{action}_total", "autoscale").inc()
            self.obs_registry.gauge("fleet_size", "autoscale").set(
                len(self._engine_ids))
        return row

    def evaluate(self, step: int = 0) -> Optional[Dict[str, Any]]:
        """One control sweep: supervise (respawn crashed engines), then at
        most ONE scale action.  Returns the scale row, or None."""
        if self.supervisor is not None:
            self.supervisor.poll(step=step)
        load = self.load_fn()
        depth = float(load.get("depth_frac", 0.0))
        p99 = load.get("p99_ms")
        hot = depth >= self.policy.up_depth or (
            self.policy.p99_ms > 0 and p99 is not None
            and p99 >= self.policy.p99_ms)
        cold = depth <= self.policy.down_depth and not hot
        if self.clock() - self._t_last_action < self.policy.cooldown_s:
            # breaches observed DURING cooldown don't count toward patience:
            # they mostly measure the fleet mid-warmup, and banking them
            # would let the first post-cooldown evaluate act instantly —
            # the double-scale the cooldown exists to prevent.  The clock
            # restarts clean when the window closes.
            self._breach_up = 0
            self._breach_down = 0
            return None
        self._breach_up = self._breach_up + 1 if hot else 0
        self._breach_down = self._breach_down + 1 if cold else 0
        if (self._breach_up >= self.policy.patience
                and len(self._engine_ids) < self.policy.max_engines):
            engine_id = self._next_id
            self._next_id += 1
            if self.supervisor is not None:
                self.supervisor.register(
                    f"engine{engine_id}",
                    lambda epoch, eid=engine_id: self.spawn_engine(eid, epoch),
                    meta={"engine": engine_id},
                )
            else:
                self.spawn_engine(engine_id, 0)
            self._engine_ids.append(engine_id)
            self._breach_up = 0
            self._t_last_action = self.clock()
            return self._emit("out", "depth" if depth >= self.policy.up_depth
                              else "p99", load, engine_id)
        if (self._breach_down >= self.policy.patience
                and len(self._engine_ids) > self.policy.min_engines):
            # shrink the newest engine: the oldest have the warmest caches
            # and the longest-observed health record
            engine_id = self._engine_ids.pop()
            if self.supervisor is not None:
                # release BEFORE stopping: the deliberate exit must never
                # race a poll() into a spurious actor_dead/respawn
                self.supervisor.release(f"engine{engine_id}")
            self.stop_engine(engine_id)
            self._breach_down = 0
            self._t_last_action = self.clock()
            return self._emit("in", "idle", load, engine_id)
        return None


class _AliveProc:
    """Proc-like for an engine the harness runs in-process and has not
    killed: the supervisor sees it running until the harness swaps in a
    real liveness probe."""

    def poll(self) -> Optional[int]:
        return None

    def kill(self) -> None:
        pass
