"""Front router: admission control, per-tenant QoS, least-depth dispatch.

The IMPACT lesson (arXiv:1912.00167) applied to inference: decouple producers
(clients) from consumers (engines) behind explicit bounds with explicit
staleness control.  The router is shared-nothing — all state is local
(token buckets, inflight counters, the lease view); N router processes in
front of the same engine fleet coordinate only through the lease files, so
the front tier scales horizontally by just running more of them.

Admission (all BEFORE any queueing — a shed request costs one exception, not
queue latency):

1. **per-tenant token bucket** — a flooding tenant exhausts its own refill
   rate and sheds with ``ServerOverloaded`` while every other tenant's
   bucket, and therefore throughput, is untouched;
2. **per-class inflight caps + priority reservation** — QoS classes are
   declared in priority order with an inflight share ("gold:50:0.5,..." =
   name:deadline_ms:share).  A class is capped at its share of the global
   inflight bound, and lower classes additionally cannot consume the
   headroom still reserved by higher classes — so under global pressure the
   shed order is strictly lowest-class-first and gold's share is always
   available to gold;
3. **global bounded inflight** — the fleet-wide backstop.

Dispatch is weighted least-depth: among routable engines whose weights are
within ``max_weight_lag`` of the rollout target (`StalenessFence` semantics,
per engine, role "router"), pick the minimum of
``(queue_depth + router_inflight) / lanes``.  An accepted request survives
engine death: the engine's futures fail with ``ServerClosed``, and the
router re-dispatches them to surviving engines — accepted requests are only
ever lost when NO engine remains (counted as ``lost``; the soak gates it at
zero).
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Set

from rainbow_iqn_apex_tpu.parallel.elastic import StalenessFence
from rainbow_iqn_apex_tpu.serving.batcher import (
    ServeFuture,
    ServerClosed,
    ServerOverloaded,
)
from rainbow_iqn_apex_tpu.serving.fleet.registry import (
    EngineDead,
    EngineHandle,
    EngineRegistry,
)


@dataclasses.dataclass(frozen=True)
class QoSClass:
    """One deadline tier.  ``priority`` 0 is highest (list order in the
    spec); ``share`` is the fraction of the global inflight bound this class
    is capped at AND has reserved against lower classes."""

    name: str
    deadline_ms: float
    share: float
    priority: int


def parse_qos_classes(spec: str) -> List[QoSClass]:
    """Parse "gold:50:0.5,std:200:0.35,batch:1000:0.15" (priority = list
    order, first highest) into QoSClass tiers."""
    out: List[QoSClass] = []
    for i, part in enumerate(p for p in str(spec).split(",") if p.strip()):
        fields = part.strip().split(":")
        if len(fields) != 3:
            raise ValueError(
                f"QoS class {part!r} is not name:deadline_ms:share")
        name, deadline_ms, share = fields
        out.append(QoSClass(name=name.strip(), deadline_ms=float(deadline_ms),
                            share=float(share), priority=i))
    if not out:
        raise ValueError(f"no QoS classes in {spec!r}")
    names = [c.name for c in out]
    if len(set(names)) != len(names):
        raise ValueError(f"duplicate QoS class names in {spec!r}")
    if sum(c.share for c in out) > 1.0 + 1e-9:
        raise ValueError(f"QoS shares sum past 1.0 in {spec!r}")
    return out


def _pctl(sorted_vals: Sequence[float], q: float) -> float:
    """Window percentile, the obs/registry.Histogram indexing convention."""
    n = len(sorted_vals)
    return sorted_vals[min(int(n * q), n - 1)]


class TokenBucket:
    """Seeded-clock token bucket: ``rate`` tokens/s up to ``burst``.
    ``rate <= 0`` disables (always admits)."""

    def __init__(self, rate: float, burst: int,
                 clock: Callable[[], float] = time.monotonic):
        self.rate = float(rate)
        self.burst = max(int(burst), 1)
        self.clock = clock
        self.tokens = float(self.burst)
        self._t_last = clock()

    def try_take(self, n: float = 1.0) -> bool:
        if self.rate <= 0:
            return True
        now = self.clock()
        self.tokens = min(self.tokens + (now - self._t_last) * self.rate,
                          float(self.burst))
        self._t_last = now
        if self.tokens >= n:
            self.tokens -= n
            return True
        return False


class RoutedFuture(ServeFuture):
    """The client-facing future: fulfilled by whichever engine ends up
    serving the request — possibly not the one it was first dispatched to
    (dead-engine re-dispatch is invisible to the client beyond latency)."""

    __slots__ = ("tenant", "qos", "engine_id", "tried", "_engine_cancel",
                 "trace")

    def __init__(self, obs, tenant: str, qos: QoSClass):
        super().__init__(obs)
        self.tenant = tenant
        self.qos = qos
        self.engine_id: Optional[int] = None
        self.tried: Set[int] = set()
        self._engine_cancel: Optional[Callable[[], bool]] = None
        # pipeline tracing: (trace_id, wall_t0) when this request was
        # sampled for span emission, else None
        self.trace: Optional[tuple] = None

    def cancel(self) -> bool:
        # the cancel propagates DOWN to the engine-side future so the
        # batcher skips its batch slot (serve_cancelled_total); the engine
        # future's done-callback then releases the router's inflight
        won = super().cancel()
        if won and self._engine_cancel is not None:
            self._engine_cancel()
        return won


class _Shed(ServerOverloaded):
    """Internal: ServerOverloaded carrying the shed reason for metrics."""

    def __init__(self, reason: str, detail: str):
        super().__init__(detail)
        self.reason = reason


class FrontRouter:
    """Shared-nothing front router over an `EngineRegistry`.

    ``submit(obs, tenant=..., qos=...)`` -> `RoutedFuture`; sheds raise
    ``ServerOverloaded`` (reason in ``.reason``), shutdown raises
    ``ServerClosed``.  ``housekeeping()`` (or the ``start()`` thread) drives
    the lease poll, the staleness fences and the periodic ``route`` row.
    """

    def __init__(
        self,
        registry: EngineRegistry,
        qos_classes: Sequence[QoSClass] = (),
        default_class: str = "",
        max_inflight: int = 512,
        tenant_rate: float = 0.0,
        tenant_burst: int = 64,
        max_weight_lag: int = 0,
        target_version_fn: Optional[Callable[[], int]] = None,
        logger=None,
        obs_registry=None,
        metrics_interval_s: float = 5.0,
        poll_interval_s: float = 0.25,
        reroute_window_s: float = 2.0,
        clock: Callable[[], float] = time.monotonic,
        tracer=None,
        peer_inflight_fn: Optional[Callable[[int], int]] = None,
        peer_target_fn: Optional[Callable[[], int]] = None,
    ):
        self.registry = registry
        # pipeline tracing (obs/pipeline_trace.py): always-on admit->dispatch
        # lag (`lag_router_dispatch_ms`) + sampled per-request `route` spans
        self.tracer = tracer
        self._req_seq = 0
        self.classes = list(qos_classes) or [
            QoSClass("default", 1000.0, 1.0, 0)]
        self._by_name = {c.name: c for c in self.classes}
        self.default_class = (self._by_name[default_class]
                              if default_class else self.classes[-1])
        self.max_inflight = int(max_inflight)
        self.tenant_rate = float(tenant_rate)
        self.tenant_burst = int(tenant_burst)
        self.max_weight_lag = int(max_weight_lag)
        # rollout target: what "current" means for the staleness fence; the
        # default (no rollout controller wired) fences against the freshest
        # version any routable engine serves
        self._target_version_fn = target_version_fn
        self.logger = logger
        self.obs_registry = obs_registry
        self.metrics_interval_s = float(metrics_interval_s)
        self.poll_interval_s = float(poll_interval_s)
        self.reroute_window_s = float(reroute_window_s)
        self.clock = clock
        # router federation (serving/net/gossip.py): load OTHER routers
        # gossiped for an engine joins this router's own inflight in the
        # least-depth score, so N shared-nothing fronts don't pile onto the
        # same engine between lease renewals.  None = solo router, the
        # pre-federation arithmetic bitwise.
        self.peer_inflight_fn = peer_inflight_fn
        # federated fence target (RouterGossip.peer_target_version): the
        # freshest rollout target any peer router claims joins this
        # router's own via max(), so a router that missed a publish still
        # fences engines against the fleet's truth.  None = local only.
        self.peer_target_fn = peer_target_fn
        self._lock = threading.Lock()
        self._closed = False
        self._buckets: Dict[str, TokenBucket] = {}
        self._inflight_total = 0
        self._inflight_class: Dict[str, int] = {c.name: 0 for c in self.classes}
        self._inflight_engine: Dict[int, int] = {}
        # per-engine staleness fence (PR 4 semantics, role "router"): an
        # engine behind the rollout target by more than max_weight_lag is
        # unroutable until it catches up — stale weights answer live traffic
        # exactly as silently as they corrupt replay
        self._fences: Dict[int, StalenessFence] = {}
        # window counters (route row cadence; lifetime mirrors kept too)
        self._win = self._zero_window()
        self.totals = self._zero_window()
        # bounded like ServeMetrics' window: a router whose route rows are
        # off (metrics_interval_s <= 0) must not grow latency state forever
        self._latency_ms: collections.deque = collections.deque(maxlen=65536)
        # accepted requests whose dead-engine re-dispatch found only FULL
        # survivors: parked here and retried by housekeeping until the
        # reroute window closes — momentary backpressure on a survivor must
        # not turn an accepted request into a loss (the zero-loss invariant
        # only yields when NO engine remains)
        self._retry: collections.deque = collections.deque()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._t_last_emit = clock()

    @staticmethod
    def _zero_window() -> Dict[str, Any]:
        return {
            "accepted": 0, "shed": 0, "completed": 0, "failed": 0,
            "rerouted": 0, "lost": 0, "cancelled": 0,
            "shed_by_reason": {}, "tenants": {},
        }

    @classmethod
    def from_config(cls, cfg, registry: EngineRegistry, **kwargs) -> "FrontRouter":
        return cls(
            registry,
            qos_classes=parse_qos_classes(cfg.fleet_qos_classes),
            default_class=cfg.fleet_default_class,
            max_inflight=cfg.fleet_max_inflight,
            tenant_rate=cfg.fleet_tenant_rate,
            tenant_burst=cfg.fleet_tenant_burst,
            max_weight_lag=cfg.max_weight_lag,
            metrics_interval_s=cfg.serve_metrics_interval_s,
            **kwargs,
        )

    # -------------------------------------------------------------- admission
    def _tenant_window(self, tenant: str) -> Dict[str, int]:
        t = self._win["tenants"].get(tenant)
        if t is None:
            t = {"accepted": 0, "shed": 0}
            self._win["tenants"][tenant] = t
        tt = self.totals["tenants"].get(tenant)
        if tt is None:
            self.totals["tenants"][tenant] = {"accepted": 0, "shed": 0}
        return t

    def _shed_locked(self, tenant: str, reason: str) -> None:
        self._win["shed"] += 1
        self.totals["shed"] += 1
        for w in (self._win, self.totals):
            w["shed_by_reason"][reason] = w["shed_by_reason"].get(reason, 0) + 1
        self._tenant_window(tenant)["shed"] += 1
        self.totals["tenants"][tenant]["shed"] += 1
        if self.obs_registry is not None:
            self.obs_registry.counter("route_shed_total", "router").inc()

    def _reserved_above_locked(self, qos: QoSClass) -> int:
        """Inflight headroom still reserved by classes of HIGHER priority —
        capacity a lower class may not consume (the shed order)."""
        reserved = 0
        for c in self.classes:
            if c.priority >= qos.priority:
                continue
            cap = int(c.share * self.max_inflight)
            reserved += max(0, cap - self._inflight_class[c.name])
        return reserved

    def _admit_locked(self, tenant: str, qos: QoSClass) -> Optional[str]:
        """None to admit, else the shed reason."""
        bucket = self._buckets.get(tenant)
        if bucket is None:
            bucket = TokenBucket(self.tenant_rate, self.tenant_burst,
                                 clock=self.clock)
            self._buckets[tenant] = bucket
        if not bucket.try_take():
            return "tenant_rate"
        cap = max(int(qos.share * self.max_inflight), 1)
        if self._inflight_class[qos.name] >= cap:
            return "class_inflight"
        if (self._inflight_total + 1 + self._reserved_above_locked(qos)
                > self.max_inflight):
            return "global_inflight"
        return None

    # --------------------------------------------------------------- dispatch
    def _candidates(self, exclude: Set[int]) -> List[EngineHandle]:
        """Routable engines within the weight-lag fence, least-depth first
        (depth + this router's own inflight, weighted by lane count)."""
        target = self.target_version()
        ranked = []
        with self._lock:
            inflight = dict(self._inflight_engine)
        for h in self.registry.routable():
            if h.engine_id in exclude:
                continue
            fence = self._fences.get(h.engine_id)
            if fence is None:
                fence = StalenessFence(self.max_weight_lag, metrics=self.logger,
                                       registry=self.obs_registry, role="router")
                self._fences[h.engine_id] = fence
            if not fence.observe(h.version(), target, frames_at_stake=1):
                continue
            peer_load = (self.peer_inflight_fn(h.engine_id)
                         if self.peer_inflight_fn is not None else 0)
            score = (h.depth() + inflight.get(h.engine_id, 0)
                     + peer_load) / h.lanes
            ranked.append((score, h.engine_id, h))
        ranked.sort(key=lambda t: t[:2])
        return [h for _, _, h in ranked]

    def _local_target_version(self) -> int:
        """This router's OWN view of the rollout target — what it gossips.
        Peers fold it in at READ time (target_version), never re-broadcast
        it: gossiping the federated max would echo a stale high claim
        between routers forever, past any staleness expiry."""
        if self._target_version_fn is not None:
            return int(self._target_version_fn())
        versions = [h.version() for h in self.registry.routable()]
        return max(versions, default=0)

    def target_version(self) -> int:
        peer = (int(self.peer_target_fn())
                if self.peer_target_fn is not None else 0)
        return max(self._local_target_version(), peer)

    def _dispatch(self, rf: RoutedFuture) -> bool:
        """Try engines least-depth first; bind the first that takes it."""
        for h in self._candidates(exclude=rf.tried):
            try:
                fut = h.transport.submit(rf.obs)
            except ServerOverloaded:
                # momentarily full, NOT dead: a later attempt (the retry
                # queue) may still land here once its batcher drains
                continue
            except (ServerClosed, EngineDead):
                rf.tried.add(h.engine_id)
                continue
            rf.engine_id = h.engine_id
            rf.tried.add(h.engine_id)
            if self.tracer is not None:
                # admit -> engine dispatch: ~0 on the fast path, the queue
                # wait of a parked re-route otherwise — the "router queue"
                # half of the serving lag story (batcher slot wait is the
                # other half, recorded by ServeMetrics.record_queue_wait)
                self.tracer.lag(
                    "router_dispatch_ms",
                    (time.monotonic() - rf.t_enqueue) * 1e3)
            with self._lock:
                self._inflight_engine[h.engine_id] = (
                    self._inflight_engine.get(h.engine_id, 0) + 1)
            fut.add_done_callback(
                lambda f, rf=rf, eid=h.engine_id: self._on_engine_done(rf, eid, f))
            # a client cancel must reach the ENGINE future so the batcher
            # skips its slot; wire it through the routed future
            rf._engine_cancel = fut.cancel
            return True
        return False

    def submit(self, obs, tenant: str = "default",
               qos: Optional[str] = None) -> RoutedFuture:
        """Admit + dispatch one request.  Raises ``ServerOverloaded`` on any
        shed (``.reason`` says which bound), ``ServerClosed`` after stop()."""
        if qos is not None and qos not in self._by_name:
            raise ValueError(f"unknown QoS class {qos!r}; "
                             f"valid: {sorted(self._by_name)}")
        klass = self._by_name[qos] if qos else self.default_class
        with self._lock:
            if self._closed:
                raise ServerClosed("router is shut down")
            reason = self._admit_locked(tenant, klass)
            if reason is not None:
                self._shed_locked(tenant, reason)
                raise _Shed(reason, f"router shed ({reason}) tenant={tenant} "
                                    f"class={klass.name}")
            # reserve BEFORE dispatch: concurrent submits must see the slot
            self._inflight_total += 1
            self._inflight_class[klass.name] += 1
            rid = self._req_seq
            self._req_seq += 1
        rf = RoutedFuture(obs, tenant, klass)
        if self.tracer is not None and self.tracer.sampled(rid):
            rf.trace = (self.tracer.trace_id("r", rid), time.time())
        if not self._dispatch(rf):
            with self._lock:
                self._inflight_total -= 1
                self._inflight_class[klass.name] -= 1
                n_routable = len(self.registry.routable())
                reason = "no_engine" if n_routable == 0 else "engine_backpressure"
                self._shed_locked(tenant, reason)
            raise _Shed(reason, f"router shed ({reason}) tenant={tenant}")
        with self._lock:
            self._win["accepted"] += 1
            self.totals["accepted"] += 1
            self._tenant_window(tenant)["accepted"] += 1
            self.totals["tenants"][tenant]["accepted"] += 1
        if self.obs_registry is not None:
            self.obs_registry.counter("route_accepted_total", "router").inc()
            self.obs_registry.gauge("route_inflight", "router").set(
                self._inflight_total)
        return rf

    # ------------------------------------------------- completion / re-route
    def _release_locked(self, rf: RoutedFuture) -> None:
        self._inflight_total = max(self._inflight_total - 1, 0)
        self._inflight_class[rf.qos.name] = max(
            self._inflight_class[rf.qos.name] - 1, 0)

    def _on_engine_done(self, rf: RoutedFuture, engine_id: int,
                        fut: ServeFuture) -> None:
        """Runs on the engine worker (or cancelling client) thread whenever
        an engine-side future settles."""
        with self._lock:
            n = self._inflight_engine.get(engine_id, 0)
            self._inflight_engine[engine_id] = max(n - 1, 0)
        if fut.cancelled() or rf.cancelled():
            with self._lock:
                self._release_locked(rf)
                self._win["cancelled"] += 1
                self.totals["cancelled"] += 1
            return
        err = fut._error  # settled: no race left on the slot
        if err is None:
            rf.set_result(fut._action, fut._q)
            with self._lock:
                self._release_locked(rf)
                self._win["completed"] += 1
                self.totals["completed"] += 1
                self._latency_ms.append(
                    (time.monotonic() - rf.t_enqueue) * 1e3)
            if self.tracer is not None and rf.trace is not None:
                tid, t0 = rf.trace
                self.tracer.emit_span(
                    "route", tid, t0, tenant=rf.tenant, qos=rf.qos.name,
                    engine=rf.engine_id,
                )
            return
        if isinstance(err, (ServerClosed, EngineDead)):
            # the engine died with this ACCEPTED request queued: re-route to
            # a survivor.  Eagerly mark the engine dead so concurrent
            # dispatches stop picking it before the lease times out.
            self.registry.mark_dead(engine_id)
            if self._dispatch(rf):
                self._count_reroute()
                return
            if self.registry.routable() or self.registry.revivable():
                # survivors exist but were momentarily FULL, every routable
                # engine is already in rf.tried, or the whole fleet is
                # suspect behind FRESH leases — all of which mean connection
                # flaps (injected corruption, latency) or backpressure, not
                # engine death.  Park for the housekeeping retry loop:
                # backpressure is not death, a flapped wire is not death
                # either, and declaring this accepted request lost while
                # live-leased engines remain would break the zero-loss
                # invariant (the net-chaos soak gates it).  The reroute
                # window still bounds the wait.
                with self._lock:
                    self._retry.append(
                        (rf, self.clock() + self.reroute_window_s))
                return
            self._lose(rf, engine_id)
            return
        # a real inference error: propagate to the client
        with self._lock:
            self._release_locked(rf)
            self._win["failed"] += 1
            self.totals["failed"] += 1
        rf.set_error(err)

    def _count_reroute(self) -> None:
        with self._lock:
            self._win["rerouted"] += 1
            self.totals["rerouted"] += 1
        if self.obs_registry is not None:
            self.obs_registry.counter("route_rerouted_total", "router").inc()

    def _lose(self, rf: RoutedFuture, engine_id: Optional[int]) -> None:
        with self._lock:
            self._release_locked(rf)
            self._win["lost"] += 1
            self.totals["lost"] += 1
        if self.obs_registry is not None:
            self.obs_registry.counter("route_lost_total", "router").inc()
        rf.set_error(ServerClosed(
            f"request lost: engine {engine_id} died and no engine "
            f"could take the re-route"))

    def _drain_retries(self) -> None:
        """Re-attempt parked re-routes; a request is lost only once no
        engine remains or its reroute window closes."""
        while True:
            with self._lock:
                if not self._retry:
                    return
                rf, deadline = self._retry.popleft()
            if rf.cancelled():
                with self._lock:
                    self._release_locked(rf)
                    self._win["cancelled"] += 1
                    self.totals["cancelled"] += 1
                continue
            if self._dispatch(rf):
                self._count_reroute()
                continue
            handles = self.registry.routable()
            if self.clock() >= deadline or (
                    not handles and not self.registry.revivable()):
                self._lose(rf, rf.engine_id)
                continue
            if handles and all(h.engine_id in rf.tried for h in handles):
                # one full pass failed on every live-leased engine: those
                # were connection flaps, not deaths — clear the ping-pong
                # guard so the next sweep may retry them (still bounded
                # by the reroute-window deadline above)
                rf.tried.clear()
            with self._lock:
                self._retry.appendleft((rf, deadline))
            return  # still full: let the queues drain until the next sweep

    # ----------------------------------------------------------- housekeeping
    def housekeeping(self) -> List[Dict[str, Any]]:
        """One sweep: lease poll (+ edge events), parked re-route retries,
        periodic route row."""
        events = self.registry.poll()
        self._drain_retries()
        now = self.clock()
        with self._lock:
            due = (self.metrics_interval_s > 0
                   and now - self._t_last_emit >= self.metrics_interval_s)
            if due:
                self._t_last_emit = now
        if due:
            self.emit_route_row()
        return events

    def emit_route_row(self) -> Dict[str, Any]:
        """Snapshot-and-reset the window into one ``route`` JSONL row."""
        with self._lock:
            row: Dict[str, Any] = {
                k: self._win[k]
                for k in ("accepted", "shed", "completed", "failed",
                          "rerouted", "lost", "cancelled")
            }
            row["shed_by_reason"] = dict(self._win["shed_by_reason"])
            row["tenants"] = {t: dict(v)
                              for t, v in self._win["tenants"].items()}
            row["inflight"] = self._inflight_total
            lat = sorted(self._latency_ms)
            self._win = self._zero_window()
            self._latency_ms.clear()
        if lat:
            row["latency_p50_ms"] = round(_pctl(lat, 0.5), 3)
            row["latency_p99_ms"] = round(_pctl(lat, 0.99), 3)
        row["engines"] = self.registry.snapshot()
        row["target_version"] = self.target_version()
        if self.logger is not None:
            self.logger.log("route", **row)
        return row

    def p99_ms(self) -> Optional[float]:
        """Current-window completion p99 (the autoscaler's latency input)."""
        with self._lock:
            lat = sorted(self._latency_ms)
        return _pctl(lat, 0.99) if lat else None

    def mean_depth_fraction(self, queue_bound: int) -> float:
        """Mean routable-engine queue fill fraction (the autoscaler's depth
        input); 1.0 when NO engine is routable — an engine-starved fleet
        must read as maximally loaded, not idle."""
        handles = self.registry.routable()
        if not handles:
            return 1.0
        return sum(min(h.depth() / max(queue_bound, 1), 1.0)
                   for h in handles) / len(handles)

    def inflight(self) -> int:
        with self._lock:
            return self._inflight_total

    def engine_inflight(self) -> Dict[int, int]:
        """This router's own in-flight count per engine (what it gossips)."""
        with self._lock:
            return dict(self._inflight_engine)

    def gossip_snapshot(self) -> Dict[str, Any]:
        """The federation snapshot `RouterGossip.snapshot_fn` broadcasts:
        per-engine inflight + the rollout target this router fences
        against.  Peers fold the inflight into their dispatch weights and
        max() the target into their fences."""
        with self._lock:
            inflight = {str(k): v for k, v in self._inflight_engine.items()
                        if v}
            accepted = self.totals["accepted"]
        return {"inflight": inflight,
                "target_version": self._local_target_version(),
                "accepted": accepted}

    # -------------------------------------------------------------- lifecycle
    def _run(self) -> None:
        while not self._stop.wait(self.poll_interval_s):
            try:
                self.housekeeping()
            except Exception:
                pass  # a flaky lease read must not kill the router loop

    def start(self) -> "FrontRouter":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="fleet-router", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> Dict[str, Any]:
        with self._lock:
            self._closed = True
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        self._drain_retries()  # one last placement attempt, then fail fast:
        # a parked request must not hang its client until result() times out
        while True:
            with self._lock:
                if not self._retry:
                    break
                rf, _ = self._retry.popleft()
                self._release_locked(rf)
                self._win["failed"] += 1
                self.totals["failed"] += 1
            rf.set_error(ServerClosed("router stopped with the re-route "
                                      "still parked"))
        self.emit_route_row()
        return self.stats()

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            out = {k: self.totals[k]
                   for k in ("accepted", "shed", "completed", "failed",
                             "rerouted", "lost", "cancelled")}
            out["shed_by_reason"] = dict(self.totals["shed_by_reason"])
            out["tenants"] = {t: dict(v)
                              for t, v in self.totals["tenants"].items()}
            out["inflight"] = self._inflight_total
        return out
