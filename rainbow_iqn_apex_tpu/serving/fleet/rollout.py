"""Fleet-wide weight rollout: one versioned publish, N engines, no rollback.

The training side already made single-engine swaps safe: `CheckpointWatcher`
refuses ``older_than_loaded`` steps, `WeightMailbox` versions every publish,
and the `StalenessFence` pauses anything lagging past budget (PR 4).  This
module lifts those guarantees to a FLEET:

- ``publish(params, version)`` assigns a strictly increasing fleet version
  (a backward or duplicate version is refused with a ``rollout`` row, the
  fleet-level mirror of the engine's own older_than_loaded check — the two
  layers together make a rollback impossible even under a confused
  controller);
- the publish fans out to every attached engine via ``FleetEngine.adopt``
  (engines discovered later — scale-out, respawn — are caught up by
  ``sync()``, which the router's housekeeping or the autoscaler calls after
  membership changes);
- convergence is observable: ``converged()`` is true when every ROUTABLE
  engine serves the target, and the ``rollout`` row stream records
  publish -> adopt counts -> converged with the wall-clock convergence time
  (obs_report's ``fleet:`` section reads it back).

The router closes the loop: engines behind ``max_weight_lag`` publishes are
fenced out of dispatch, so a straggler engine degrades capacity, never
answer freshness.

**Delta-compressed rollouts** (``compression="int8_delta"``,
utils/quantize.py): instead of handing every engine the full params tree,
``publish`` encodes one `WeightPacket` — a periodic full base snapshot plus
int8 per-tensor deltas against the last reconstruction — and fans THAT out
(`FleetEngine.adopt_packet`); at fleet scale the broadcast cost drops >=3x
vs fp32 full publishes (the `weight_publish` bench row / `make perf-smoke`
gate).  Packet application is bit-exact and versioned, so monotonicity,
backward refusal and the staleness fence are untouched; late joiners and
gap-hit engines are caught up by ``sync()`` replaying the chain-from-base.
``compression="off"`` (default) fans out the raw params object exactly as
before.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

from rainbow_iqn_apex_tpu.serving.fleet.registry import FleetEngine
from rainbow_iqn_apex_tpu.utils.quantize import DeltaEncoder, tree_bytes


class FleetRollout:
    """Versioned, monotone, fan-out weight publication over a fleet.

    Engines register with ``track(engine)`` (a `FleetEngine` or anything
    with ``adopt(params, version)`` + ``engine_id`` + a liveness-bearing
    ``transport``).  The controller keeps the params of the CURRENT target
    so late joiners can be synced without a re-publish.
    """

    def __init__(self, logger=None, obs_registry=None,
                 clock: Callable[[], float] = time.monotonic,
                 compression: str = "off", base_interval: int = 10,
                 tracer=None):
        self.logger = logger
        self.obs_registry = obs_registry
        self.clock = clock
        # pipeline tracing (obs/pipeline_trace.py): per-engine publish ->
        # adopt lag lands on the tracer's consumer windows (`lag` row /
        # RunHealth propagation budget); sampled versions emit adopt spans
        # under the cross-process "w<host>-<version>" trace id
        self.tracer = tracer
        self.compression = compression
        self._codec = (DeltaEncoder(base_interval)
                       if compression == "int8_delta" else None)
        self._lock = threading.Lock()
        self._engines: Dict[int, Any] = {}
        self.target_version = 0
        self._target_params: Any = None
        self._t_publish: Optional[float] = None
        self._converged_emitted = True
        self.refused = 0
        self.publishes = 0
        self.bytes_total = 0

    # ------------------------------------------------------------- membership
    def track(self, engine: FleetEngine) -> None:
        with self._lock:
            self._engines[int(engine.engine_id)] = engine

    def untrack(self, engine_id: int) -> None:
        with self._lock:
            self._engines.pop(int(engine_id), None)

    def version(self) -> int:
        """The rollout target — what the router's staleness fence measures
        engine lag against."""
        return self.target_version

    def reconstructed_digest(self) -> Optional[str]:
        """sha256 of the fp32 tree every in-sync subscriber should hold —
        compared against each engine's ``served_digest`` to assert the
        cross-host rollout landed bit-exact (net_smoke's convergence gate).
        Compressed rollouts digest the encoder's closed-loop reconstruction;
        uncompressed ones digest the target params directly.  None before
        the first publish."""
        from rainbow_iqn_apex_tpu.utils.quantize import tree_digest

        with self._lock:
            if self._codec is not None and self._codec.version >= 0:
                return tree_digest(self._codec.reconstructed())
            if self._target_params is not None:
                return tree_digest(self._target_params)
        return None

    # ---------------------------------------------------------------- publish
    def _row(self, event: str, **fields: Any) -> Dict[str, Any]:
        row = {"event": event, "version": self.target_version, **fields}
        if self.logger is not None:
            self.logger.log("rollout", **row)
        return row

    def publish(self, params: Any, version: Optional[int] = None) -> Dict[str, Any]:
        """Fan a new weight version out to every tracked engine.

        ``version`` defaults to target+1; an explicit version must be
        STRICTLY greater than the current target — the fleet never moves
        backwards, and a duplicate publish is a controller bug, not a no-op
        to paper over."""
        with self._lock:
            new_version = (self.target_version + 1 if version is None
                           else int(version))
            if new_version <= self.target_version:
                self.refused += 1
                row = self._row("refused_backward", refused=new_version,
                                target=self.target_version)
                if self.obs_registry is not None:
                    self.obs_registry.counter(
                        "rollout_refused_total", "rollout").inc()
                return row
            self.target_version = new_version
            self._target_params = params
            self._t_publish = self.clock()
            self._converged_emitted = False
            self.publishes += 1
            # delta compression: encode ONCE under the lock (the encoder is
            # closed-loop stateful — a racing second publish must see the
            # chain this one appended), fan the value-object packet out to N
            # engines lock-free below
            packet = (self._codec.encode(params, new_version)
                      if self._codec is not None else None)
            engines = list(self._engines.values())
        if self.obs_registry is not None:
            self.obs_registry.gauge("rollout_target_version", "rollout").set(
                self.target_version)
        if self.tracer is not None:
            self.tracer.note_publish(new_version)
        adopted, failed = self._fan_out(engines, params, new_version, packet)
        bytes_fp32 = tree_bytes(params)
        shipped = packet.nbytes() if packet is not None else bytes_fp32
        self.bytes_total += shipped
        if self.obs_registry is not None:
            self.obs_registry.counter(
                "publish_bytes_total", "rollout").inc(shipped)
        row = self._row("publish", engines=len(engines), adopted=adopted,
                        failed=failed, bytes=shipped, bytes_fp32=bytes_fp32,
                        compression=self.compression)
        self.maybe_emit_converged()
        return row

    def _fan_out(self, engines: List[Any], params: Any, version: int,
                 packet: Any = None) -> "tuple[int, int]":
        adopted = failed = 0
        for engine in engines:
            try:
                t0 = time.time()
                if packet is not None and hasattr(engine, "adopt_packet"):
                    engine.adopt_packet(packet)
                else:
                    engine.adopt(params, version)
                adopted += 1
                if self.tracer is not None:
                    eid = int(getattr(engine, "engine_id", -1))
                    self.tracer.note_adopt(f"engine{eid}", version)
                    if self.tracer.sampled(version):
                        self.tracer.emit_span(
                            "adopt", self.tracer.trace_id("w", version), t0,
                            version=version, consumer=f"engine{eid}",
                        )
            except Exception:
                # a failed adopt (dying engine, mid-kill race, or a
                # delta-chain gap on an engine that missed packets) is not
                # fatal to the rollout: the router fences the straggler and
                # sync() retries it; the publish row carries the count
                failed += 1
        return adopted, failed

    def sync(self) -> int:
        """Catch up engines behind the current target (late joiners from
        scale-out or respawn).  Returns how many adopted.  Compressed
        rollouts replay the chain-from-base (`adopt_chain` skips packets an
        engine already holds, so catch-up is idempotent and bit-exact)."""
        with self._lock:
            if self._target_params is None:
                return 0
            params, version = self._target_params, self.target_version
            chain = self._codec.chain() if self._codec is not None else None
            behind = [e for e in self._engines.values()
                      if e.transport.version() < version]
        adopted = 0
        for engine in behind:
            try:
                if chain is not None and hasattr(engine, "adopt_chain"):
                    engine.adopt_chain(chain)
                else:
                    engine.adopt(params, version)
                adopted += 1
            except Exception:
                pass  # still behind; the next sync retries
        if adopted:
            self._row("sync", adopted=adopted)
        self.maybe_emit_converged()
        return adopted

    # ------------------------------------------------------------ convergence
    def engine_versions(self) -> Dict[int, int]:
        with self._lock:
            return {eid: e.transport.version()
                    for eid, e in self._engines.items()}

    def converged(self) -> bool:
        """Every LIVE tracked engine serves the target version, and at
        least ONE does.  Dead engines don't block convergence — their lease
        eviction removes them from routing, and a respawn re-enters through
        sync() — but a fleet with NOTHING live serving the target has not
        converged: an all-engines-down publish must not emit a bogus
        converged row the moment it lands."""
        with self._lock:
            engines = list(self._engines.values())
            target = self.target_version
        if target <= 0:
            return True  # nothing ever published: vacuously converged
        live = [e for e in engines if e.transport.alive()]
        if not live:
            return False
        return all(e.transport.version() >= target for e in live)

    def maybe_emit_converged(self) -> Optional[Dict[str, Any]]:
        """Emit the one ``converged`` row per publish (idempotent)."""
        with self._lock:
            if self._converged_emitted or self._t_publish is None:
                return None
        if not self.converged():
            return None
        with self._lock:
            if self._converged_emitted:
                return None
            self._converged_emitted = True
            dt = self.clock() - self._t_publish
        if self.obs_registry is not None:
            self.obs_registry.gauge(
                "rollout_convergence_s", "rollout").set(round(dt, 3))
        return self._row("converged", convergence_s=round(dt, 3),
                         versions={str(k): v
                                   for k, v in self.engine_versions().items()})

    def wait_converged(self, timeout_s: float = 10.0,
                       poll_s: float = 0.05) -> bool:
        """Poll-with-sync until the fleet converges or the budget runs out."""
        deadline = self.clock() + float(timeout_s)
        while True:
            self.sync()
            if self.converged():
                self.maybe_emit_converged()
                return True
            if self.clock() >= deadline:
                return False
            time.sleep(poll_s)
