"""serving/fleet: front router + autoscaled engine fleet (docs/SERVING.md).

The horizontal layer over PR 1's single `PolicyServer`: a shared-nothing
`FrontRouter` (admission control, per-tenant QoS, least-depth dispatch,
weight-lag fencing), an `EngineRegistry` where engines self-register through
the PR-4 lease machinery, an `Autoscaler` with hysteresis + supervised
respawn, and a `FleetRollout` that publishes weights fleet-wide with
monotone versions.  Import-time jax-free: a router front-end never pays the
device-runtime import tax.
"""

from rainbow_iqn_apex_tpu.serving.fleet.autoscale import Autoscaler, ScalePolicy
from rainbow_iqn_apex_tpu.serving.fleet.registry import (
    EngineDead,
    EngineHandle,
    EngineRegistry,
    FleetEngine,
    ServerTransport,
)
from rainbow_iqn_apex_tpu.serving.fleet.rollout import FleetRollout
from rainbow_iqn_apex_tpu.serving.fleet.router import (
    FrontRouter,
    QoSClass,
    RoutedFuture,
    TokenBucket,
    parse_qos_classes,
)

__all__ = [
    "Autoscaler",
    "EngineDead",
    "EngineHandle",
    "EngineRegistry",
    "FleetEngine",
    "FleetRollout",
    "FrontRouter",
    "QoSClass",
    "RoutedFuture",
    "ScalePolicy",
    "ServerTransport",
    "TokenBucket",
    "parse_qos_classes",
]
