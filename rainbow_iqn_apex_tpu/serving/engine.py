"""Sharded bucketed inference engine: the device half of the policy server.

Reuses the actor-side machinery the Ape-X driver already trusts
(parallel/mesh.py lane sharding + ops/learn.build_act_step): request batches
are padded to one of a few fixed bucket sizes and dispatched through ONE
jitted act step whose input sharding spreads rows over the actor mesh.

Why buckets: jit compiles per input shape.  Serving traffic produces every
batch size from 1..B, and letting each distinct size reach XLA means a
compile storm exactly when the server is busiest.  Padding to a small fixed
set keeps the executable count == bucket count forever (asserted in tests
via the jit cache size), at the cost of a few wasted padded rows.

Why an atomic params reference: hot-swap.  ``load_params`` device_puts the
new tree OFF the worker thread and then swaps one Python reference — the
in-flight dispatch keeps the old tree (XLA holds its own buffers), the next
batch picks up the new one, and no request ever observes a half-written
tree.  This is the serving-side mirror of the learner->actor publish in
parallel/apex.py.
"""

from __future__ import annotations

import threading
import time
from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from rainbow_iqn_apex_tpu.config import Config
from rainbow_iqn_apex_tpu.ops.learn import build_act_step
from rainbow_iqn_apex_tpu.parallel.mesh import actor_mesh, batch_sharding, replicated
from rainbow_iqn_apex_tpu.serving.batcher import pick_bucket
from rainbow_iqn_apex_tpu.utils.quantize import (
    check_mode,
    greedy_agreement,
    quantize_for_mode,
    wrap_act_quantized,
)


def _quantizer_for(mode: str):
    """Top-level closure over the (static) quant mode so jit sees one stable
    callable per engine — `functools.partial` on a lambda would too, but a
    named def keeps tracebacks readable."""
    def quantize(params):
        return quantize_for_mode(params, mode)

    return quantize


def fit_buckets(buckets: Sequence[int], n_devices: int) -> List[int]:
    """Round each requested bucket up to a lane-shardable size (a multiple of
    the actor-mesh device count) and dedupe; order stays ascending."""
    fitted = sorted({max(-(-int(b) // n_devices) * n_devices, n_devices)
                     for b in buckets})
    if not fitted:
        raise ValueError("need at least one batch bucket")
    return fitted


class InferenceEngine:
    """Bucketed, lane-sharded policy inference with atomically swappable
    params.

    mode: "greedy" acts without noisy-net noise (eval-time behaviour);
    "noisy" keeps noise on (exploration-flavoured eval, cfg.eval_noisy
    semantics).  Taus are sampled fresh per dispatch in both modes, as the
    acting path always does.

    Quantized inference (``cfg.serve_quantize`` = "int8"/"fp8",
    utils/quantize.py): every ``load_params`` additionally stages a
    quantized copy whose act step dequantizes **inside each bucket's own
    XLA executable** (weights live int8/fp8 in HBM; the scale multiply
    fuses into the first use of each tensor), and a greedy-action agreement
    gate against the fp32 policy on the calibration batch decides which
    copy serves: agreement >= ``cfg.quant_agreement_min`` activates the
    quantized path, below-threshold falls back to fp32 and emits one
    reasoned ``quant_fallback`` row (via ``quant_log``).  The fp32 tree is
    retained for future gates — the win is per-dispatch bandwidth/compute,
    not resident memory.  "off" (default) takes exactly the pre-quant code
    path.  The gate key is fixed (derived from the seed), so fp32 and
    quantized actions are compared under identical taus/noise and the gate
    is deterministic per params version.
    """

    def __init__(
        self,
        cfg: Config,
        num_actions: int,
        params: Any,
        devices: Optional[Sequence[jax.Device]] = None,
        buckets: Optional[Sequence[int]] = None,
        mode: str = "greedy",
        calib_obs: Optional[np.ndarray] = None,
        quant_log: Optional[Any] = None,
    ):
        if mode not in ("greedy", "noisy"):
            raise ValueError(f"unknown serve mode {mode!r}")
        self.cfg = cfg
        self.num_actions = num_actions
        self.mode = mode
        devs = list(devices if devices is not None else jax.devices())
        self.mesh = actor_mesh(devs)
        self.n_devices = len(devs)
        self._rep = replicated(self.mesh)
        self._lane_sh = batch_sharding(self.mesh, "actor")
        self.buckets = fit_buckets(
            buckets if buckets is not None else parse_buckets(cfg.serve_batch_buckets),
            self.n_devices,
        )
        act_fn = build_act_step(cfg, num_actions, use_noise=(mode == "noisy"))
        self._act = jax.jit(
            act_fn,
            in_shardings=(self._rep, self._lane_sh, self._rep),
            out_shardings=(self._lane_sh, self._lane_sh),
        )
        self._key = jax.random.PRNGKey(cfg.seed + 4099)
        self._key_lock = threading.Lock()
        self._swap_lock = threading.Lock()
        # ---- quantized inference mode (docs/PERFORMANCE.md "quantization")
        self.quant_mode = check_mode(getattr(cfg, "serve_quantize", "off"))
        self.quant_agreement_min = float(
            getattr(cfg, "quant_agreement_min", 0.99))
        self.quant_log = quant_log
        self.quant_active = False
        self.quant_agreement: Optional[float] = None
        self.quant_fallbacks = 0
        self._qparams = None
        self._calib_obs = None if calib_obs is None else np.asarray(calib_obs)
        if self.quant_mode != "off":
            self._act_q = jax.jit(
                wrap_act_quantized(act_fn),
                in_shardings=(self._rep, self._lane_sh, self._rep),
                out_shardings=(self._lane_sh, self._lane_sh),
            )
            self._quantize = jax.jit(
                _quantizer_for(self.quant_mode),
                out_shardings=self._rep,
            )
            self._gate_key = jax.random.PRNGKey(cfg.seed + 8221)
        self._params = jax.device_put(params, self._rep)
        self.params_version = 0
        if self.quant_mode != "off":
            self._stage_quantized(self._params)
        # staleness monitoring (the serving mirror of the training side's
        # weight-version stamp, parallel/elastic.py): when the weights last
        # changed, so healthz can report weights_age_s externally
        self.weights_loaded_at = time.monotonic()

    # ------------------------------------------------------------- hot swap
    def load_params(self, params: Any) -> int:
        """Stage ``params`` onto the actor mesh, then atomically swap the
        reference the next dispatch reads.  Safe to call from any thread
        while inference runs; returns the new params version.

        Staging happens UNDER the swap lock: two concurrent swaps (watcher
        poll + direct learner push) must land in call order, or a slow
        stage of older params could overwrite a fresher swap.  With a
        quantized mode on, the quantized copy is staged and gated under the
        same lock, so a dispatch can never pair new fp32 params with a
        stale quantized tree."""
        with self._swap_lock:
            self._params = jax.device_put(params, self._rep)
            if self.quant_mode != "off":
                self._stage_quantized(self._params)
            self.params_version += 1
            self.weights_loaded_at = time.monotonic()
            return self.params_version

    # ------------------------------------------------- quantized inference
    def set_calibration(self, calib_obs: np.ndarray) -> None:
        """Provide/replace the calibration observations ([n, H, W, C] u8,
        ideally drawn from real traffic or replay statistics) and re-run
        the gate against the currently staged params."""
        self._calib_obs = np.asarray(calib_obs)
        if self.quant_mode != "off":
            with self._swap_lock:
                self._stage_quantized(self._params)

    def _emit_quant(self, kind: str, **fields: Any) -> None:
        if self.quant_log is not None:
            try:
                self.quant_log(kind, **fields)
            except Exception:
                pass  # observability must never block a swap

    def _stage_quantized(self, staged_params: Any) -> None:
        """Quantize ``staged_params`` and run the agreement gate.  Called
        under the swap lock.  No calibration batch yet -> the quantized
        path stays off quietly (not a fallback: the gate is unevaluable,
        and serving unvetted quantized weights is exactly what the gate
        exists to prevent).

        The quantized tree stays a LOCAL until the gate has ruled: a
        dispatch racing this stage must keep serving the previous VETTED
        quantized tree (merely stale — the load_params in-flight-dispatch
        semantics), never the new unvetted one.  Only a passed gate
        publishes the (qparams, active) pair."""
        qparams = self._quantize(staged_params)
        if self._calib_obs is None:
            self.quant_active = False
            self._qparams = qparams  # unused while inactive; kept fresh
            return
        # clamp to the largest bucket: the gate rides the same bucketed
        # executables live traffic uses, and an over-sized calibration
        # batch (RUNBOOK suggests 256+) must narrow, not crash the swap
        obs = self._calib_obs[: self.buckets[-1]]
        n = obs.shape[0]
        bucket = self.bucket_for(n)
        if bucket != n:
            pad = np.broadcast_to(obs[:1], (bucket - n, *obs.shape[1:]))
            obs = np.concatenate([obs, pad], axis=0)
        obs_dev = jnp.asarray(obs)
        a32, _ = self._act(self._params, obs_dev, self._gate_key)
        aq, _ = self._act_q(qparams, obs_dev, self._gate_key)
        agreement = greedy_agreement(
            np.asarray(a32)[:n], np.asarray(aq)[:n])
        self.quant_agreement = agreement
        passed = agreement >= self.quant_agreement_min
        if passed:
            self._qparams = qparams
            self.quant_active = True
            self._emit_quant(
                "quant", event="gate", mode=self.quant_mode, active=True,
                agreement=round(agreement, 6),
                threshold=self.quant_agreement_min, calib_batch=int(n),
            )
        else:
            was_active = self.quant_active
            self.quant_active = False
            self._qparams = qparams  # unused while inactive; kept fresh
            self.quant_fallbacks += 1
            self._emit_quant(
                "quant_fallback", reason="agreement_below_min",
                mode=self.quant_mode, agreement=round(agreement, 6),
                threshold=self.quant_agreement_min, calib_batch=int(n),
                was_active=was_active,
            )

    def quant_state(self) -> dict:
        """Live quantization status (healthz / stats surface)."""
        return {
            "quant_mode": self.quant_mode,
            "quant_active": self.quant_active,
            "quant_agreement": self.quant_agreement,
            "quant_fallbacks": self.quant_fallbacks,
        }

    def weights_age_s(self) -> float:
        """Seconds since the served weights last changed."""
        return time.monotonic() - self.weights_loaded_at

    @property
    def params(self) -> Any:
        return self._params

    # ------------------------------------------------------------ inference
    def _next_key(self):
        with self._key_lock:
            self._key, k = jax.random.split(self._key)
        return k

    def bucket_for(self, n: int) -> int:
        return pick_bucket(self.buckets, n)

    def infer(self, obs: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """obs [n, H, W, C] uint8, n <= max bucket -> (actions [n], q [n, A]).

        Pads to the smallest bucket (repeating row 0 — real pixels keep the
        padded rows' compute on the same numeric path as live traffic) and
        slices the padding back off on the host.
        """
        n = obs.shape[0]
        bucket = self.bucket_for(n)
        if bucket != n:
            pad = np.broadcast_to(obs[:1], (bucket - n, *obs.shape[1:]))
            obs = np.concatenate([obs, pad], axis=0)
        if self.quant_active:
            a, q = self._act_q(self._qparams, jnp.asarray(obs), self._next_key())
        else:
            a, q = self._act(self._params, jnp.asarray(obs), self._next_key())
        return np.asarray(a)[:n], np.asarray(q)[:n]

    # -------------------------------------------------------- observability
    def compiled_executables(self) -> Optional[int]:
        """How many distinct executables the act step has compiled — the
        no-recompile-per-request guarantee is ``<= len(self.buckets)``.
        Returns None when the jit cache API is unavailable (jax internals
        moved) so the guard test can skip LOUDLY instead of passing
        vacuously."""
        try:
            return int(self._act._cache_size())
        except AttributeError:
            return None


def parse_buckets(spec: str) -> List[int]:
    """Parse "8,16,32,64" into [8, 16, 32, 64]."""
    out = [int(p) for p in str(spec).split(",") if p.strip()]
    if not out:
        raise ValueError(f"no batch buckets in {spec!r}")
    return out
