"""Sharded bucketed inference engine: the device half of the policy server.

Reuses the actor-side machinery the Ape-X driver already trusts
(parallel/mesh.py lane sharding + ops/learn.build_act_step): request batches
are padded to one of a few fixed bucket sizes and dispatched through ONE
jitted act step whose input sharding spreads rows over the actor mesh.

Why buckets: jit compiles per input shape.  Serving traffic produces every
batch size from 1..B, and letting each distinct size reach XLA means a
compile storm exactly when the server is busiest.  Padding to a small fixed
set keeps the executable count == bucket count forever (asserted in tests
via the jit cache size), at the cost of a few wasted padded rows.

Why an atomic params reference: hot-swap.  ``load_params`` device_puts the
new tree OFF the worker thread and then swaps one Python reference — the
in-flight dispatch keeps the old tree (XLA holds its own buffers), the next
batch picks up the new one, and no request ever observes a half-written
tree.  This is the serving-side mirror of the learner->actor publish in
parallel/apex.py.
"""

from __future__ import annotations

import threading
import time
from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from rainbow_iqn_apex_tpu.config import Config
from rainbow_iqn_apex_tpu.ops.learn import build_act_step
from rainbow_iqn_apex_tpu.parallel.mesh import actor_mesh, batch_sharding, replicated
from rainbow_iqn_apex_tpu.serving.batcher import pick_bucket


def fit_buckets(buckets: Sequence[int], n_devices: int) -> List[int]:
    """Round each requested bucket up to a lane-shardable size (a multiple of
    the actor-mesh device count) and dedupe; order stays ascending."""
    fitted = sorted({max(-(-int(b) // n_devices) * n_devices, n_devices)
                     for b in buckets})
    if not fitted:
        raise ValueError("need at least one batch bucket")
    return fitted


class InferenceEngine:
    """Bucketed, lane-sharded policy inference with atomically swappable
    params.

    mode: "greedy" acts without noisy-net noise (eval-time behaviour);
    "noisy" keeps noise on (exploration-flavoured eval, cfg.eval_noisy
    semantics).  Taus are sampled fresh per dispatch in both modes, as the
    acting path always does.
    """

    def __init__(
        self,
        cfg: Config,
        num_actions: int,
        params: Any,
        devices: Optional[Sequence[jax.Device]] = None,
        buckets: Optional[Sequence[int]] = None,
        mode: str = "greedy",
    ):
        if mode not in ("greedy", "noisy"):
            raise ValueError(f"unknown serve mode {mode!r}")
        self.cfg = cfg
        self.num_actions = num_actions
        self.mode = mode
        devs = list(devices if devices is not None else jax.devices())
        self.mesh = actor_mesh(devs)
        self.n_devices = len(devs)
        self._rep = replicated(self.mesh)
        self._lane_sh = batch_sharding(self.mesh, "actor")
        self.buckets = fit_buckets(
            buckets if buckets is not None else parse_buckets(cfg.serve_batch_buckets),
            self.n_devices,
        )
        self._act = jax.jit(
            build_act_step(cfg, num_actions, use_noise=(mode == "noisy")),
            in_shardings=(self._rep, self._lane_sh, self._rep),
            out_shardings=(self._lane_sh, self._lane_sh),
        )
        self._key = jax.random.PRNGKey(cfg.seed + 4099)
        self._key_lock = threading.Lock()
        self._swap_lock = threading.Lock()
        self._params = jax.device_put(params, self._rep)
        self.params_version = 0
        # staleness monitoring (the serving mirror of the training side's
        # weight-version stamp, parallel/elastic.py): when the weights last
        # changed, so healthz can report weights_age_s externally
        self.weights_loaded_at = time.monotonic()

    # ------------------------------------------------------------- hot swap
    def load_params(self, params: Any) -> int:
        """Stage ``params`` onto the actor mesh, then atomically swap the
        reference the next dispatch reads.  Safe to call from any thread
        while inference runs; returns the new params version.

        Staging happens UNDER the swap lock: two concurrent swaps (watcher
        poll + direct learner push) must land in call order, or a slow
        stage of older params could overwrite a fresher swap."""
        with self._swap_lock:
            self._params = jax.device_put(params, self._rep)
            self.params_version += 1
            self.weights_loaded_at = time.monotonic()
            return self.params_version

    def weights_age_s(self) -> float:
        """Seconds since the served weights last changed."""
        return time.monotonic() - self.weights_loaded_at

    @property
    def params(self) -> Any:
        return self._params

    # ------------------------------------------------------------ inference
    def _next_key(self):
        with self._key_lock:
            self._key, k = jax.random.split(self._key)
        return k

    def bucket_for(self, n: int) -> int:
        return pick_bucket(self.buckets, n)

    def infer(self, obs: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """obs [n, H, W, C] uint8, n <= max bucket -> (actions [n], q [n, A]).

        Pads to the smallest bucket (repeating row 0 — real pixels keep the
        padded rows' compute on the same numeric path as live traffic) and
        slices the padding back off on the host.
        """
        n = obs.shape[0]
        bucket = self.bucket_for(n)
        if bucket != n:
            pad = np.broadcast_to(obs[:1], (bucket - n, *obs.shape[1:]))
            obs = np.concatenate([obs, pad], axis=0)
        a, q = self._act(self._params, jnp.asarray(obs), self._next_key())
        return np.asarray(a)[:n], np.asarray(q)[:n]

    # -------------------------------------------------------- observability
    def compiled_executables(self) -> Optional[int]:
        """How many distinct executables the act step has compiled — the
        no-recompile-per-request guarantee is ``<= len(self.buckets)``.
        Returns None when the jit cache API is unavailable (jax internals
        moved) so the guard test can skip LOUDLY instead of passing
        vacuously."""
        try:
            return int(self._act._cache_size())
        except AttributeError:
            return None


def parse_buckets(spec: str) -> List[int]:
    """Parse "8,16,32,64" into [8, 16, 32, 64]."""
    out = [int(p) for p in str(spec).split(",") if p.strip()]
    if not out:
        raise ValueError(f"no batch buckets in {spec!r}")
    return out
