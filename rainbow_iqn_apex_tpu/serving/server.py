"""PolicyServer: batched low-latency `act(observation) -> action` for many
concurrent clients, with weight hot-swap.

Composition (one worker thread owns the device; clients only touch the
queue):

    client threads --submit--> MicroBatcher (bounded queue, deadline)
                                   |
                              worker thread --pad to bucket--> InferenceEngine
                                   |                               ^
                              fulfil futures             CheckpointWatcher /
                              + ServeMetrics             reload() hot-swap

Transport is in-process by design: the Ape-X mesh already colocates acting
with the chips, so the serving seam is a Python API that a network front-end
(or the actor loop itself) calls.  Everything latency-relevant — coalescing,
padding, shedding, swap — is below this seam and covered by tier-1 CPU
tests; a socket listener is a thin adapter on top.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import numpy as np

from rainbow_iqn_apex_tpu.config import Config
from rainbow_iqn_apex_tpu.obs.export import ObsHTTPServer
from rainbow_iqn_apex_tpu.serving.batcher import (
    MicroBatcher,
    ServeFuture,
    ServerClosed,
)
from rainbow_iqn_apex_tpu.serving.engine import InferenceEngine, parse_buckets
from rainbow_iqn_apex_tpu.serving.metrics import ServeMetrics
from rainbow_iqn_apex_tpu.serving.swap import (
    CheckpointWatcher,
    params_template,
    restore_params,
)
from rainbow_iqn_apex_tpu.utils.checkpoint import Checkpointer
from rainbow_iqn_apex_tpu.utils.logging import MetricsLogger


class PolicyServer:
    """Serve IQN policy inference to concurrent clients.

    Lifecycle: construct -> start() -> submit()/act() from any thread ->
    stop().  stop() drains queued requests before exiting (graceful), unless
    ``drain=False`` fails them immediately.
    """

    def __init__(
        self,
        cfg: Config,
        num_actions: int,
        params: Any,
        devices: Optional[Sequence[jax.Device]] = None,
        checkpointer: Optional[Checkpointer] = None,
        state_shape: Optional[Tuple[int, ...]] = None,
        template: Optional[Any] = None,
        metrics_path: Optional[str] = None,
        echo_metrics: bool = False,
    ):
        self.cfg = cfg
        self.num_actions = num_actions
        self.metrics = ServeMetrics(
            MetricsLogger(metrics_path, run_id=cfg.run_id, echo=echo_metrics)
            if metrics_path
            else None
        )
        # live fleet telemetry (obs/net/): serving hosts stream their rows
        # + registry snapshots to the fleet collector too; None (nothing
        # constructed) whenever the plane is off or there is no logger
        self.obs_relay = None
        if self.metrics.logger is not None and getattr(cfg, "obs_net", False):
            from rainbow_iqn_apex_tpu.obs.net.relay import ObsRelay

            self.obs_relay = ObsRelay.attach(
                cfg, self.metrics.logger, registry=self.metrics.registry,
                role="serve")
        self._obs_shape_early = tuple(state_shape or cfg.state_shape)
        # calibration for the quantization agreement gate: callers with real
        # traffic/replay frames pass them via engine.set_calibration; the
        # default synthesizes seeded uniform frames, which exercise the full
        # numeric path (conv -> taus -> heads) even if they are not the
        # served distribution (docs/PERFORMANCE.md "quantization")
        calib_obs = None
        if getattr(cfg, "serve_quantize", "off") != "off":
            n = max(int(getattr(cfg, "quant_calib_batch", 64)), 1)
            calib_obs = np.random.default_rng(cfg.seed + 7).integers(
                0, 255, (n, *self._obs_shape_early), dtype=np.uint8
            )
        self.engine = InferenceEngine(
            cfg,
            num_actions,
            params,
            devices=devices,
            buckets=parse_buckets(cfg.serve_batch_buckets),
            mode=cfg.serve_mode,
            calib_obs=calib_obs,
            quant_log=self._quant_log,
        )
        self.batcher = MicroBatcher(
            self.engine.buckets,
            deadline_s=cfg.serve_deadline_ms / 1e3,
            queue_bound=cfg.serve_queue_bound,
            metrics=self.metrics,
        )
        self.watcher: Optional[CheckpointWatcher] = None
        self._owns_checkpointer = False  # from_checkpoint sets it; stop() closes
        if checkpointer is not None:
            self.watcher = CheckpointWatcher(
                checkpointer,
                template if template is not None
                else params_template(cfg, num_actions, state_shape=state_shape),
                self.engine.load_params,
                poll_interval_s=cfg.serve_swap_poll_s,
                metrics=self.metrics,
            )
        self._obs_shape = tuple(state_shape or cfg.state_shape)
        self._metrics_interval_s = max(cfg.serve_metrics_interval_s, 0.0)
        self._worker: Optional[threading.Thread] = None
        self._started = False
        # obs/: /metrics (Prometheus text off the shared registry ServeMetrics
        # records into) + /healthz (shed/queue/worker-liveness status)
        self.obs_http: Optional[ObsHTTPServer] = None
        if int(getattr(cfg, "obs_http_port", 0) or 0) > 0:
            self.obs_http = ObsHTTPServer(
                self.metrics.registry, self.healthz, port=cfg.obs_http_port
            )

    def _quant_log(self, kind: str, **fields: Any) -> None:
        """Engine gate events -> the shared metrics surface: schema rows
        (`quant` / `quant_fallback`) plus registry gauges so /metrics and
        RunHealth see the same numbers."""
        reg = self.metrics.registry
        if kind == "quant_fallback":
            reg.counter("quant_fallback_total", "serve").inc()
        if fields.get("agreement") is not None:
            reg.gauge("quant_action_agreement", "serve").set(
                float(fields["agreement"]))
        if self.metrics.logger is not None:
            self.metrics.logger.log(kind, **fields)

    @classmethod
    def from_checkpoint(
        cls,
        cfg: Config,
        num_actions: int,
        checkpoint_dir: str,
        state_shape: Optional[Tuple[int, ...]] = None,
        **kwargs: Any,
    ) -> "PolicyServer":
        """Boot a server straight off a learner's checkpoint directory; the
        watcher then follows that directory for newer steps."""
        ckpt = Checkpointer(checkpoint_dir)
        # one template: init_train_state is a full network+optimizer trace,
        # too expensive to rebuild again inside __init__ for the watcher
        try:
            template = params_template(cfg, num_actions, state_shape=state_shape)
            params = restore_params(ckpt, template)
        except BaseException:
            ckpt.close()  # a supervisor retrying boot must not leak managers
            raise
        server = cls(
            cfg,
            num_actions,
            params,
            checkpointer=ckpt,
            state_shape=state_shape,
            template=template,
            **kwargs,
        )
        server._owns_checkpointer = True
        server.watcher.last_step = ckpt.latest_step()
        return server

    # -------------------------------------------------------------- lifecycle
    def warmup(self) -> int:
        """Compile every bucket's executable now, not on first live traffic —
        an uncompiled bucket charges full XLA compile time (well past act()'s
        default timeout on a real network) to whichever request hits it first,
        and corrupts the latency percentiles.  Idempotent; returns the bucket
        count."""
        for b in self.engine.buckets:
            self.engine.infer(np.zeros((b, *self._obs_shape), np.uint8))
        return len(self.engine.buckets)

    def start(self, warmup: bool = True) -> "PolicyServer":
        if self._started:
            return self
        if warmup:
            self.warmup()
        self._started = True
        self._worker = threading.Thread(
            target=self._serve_loop, name="serve-worker", daemon=True
        )
        self._worker.start()
        if self.watcher is not None:
            self.watcher.start()
        if self.obs_http is not None:
            self.obs_http.start()
        return self

    def stop(self, drain: bool = True) -> Dict[str, Any]:
        """Shut down: refuse new requests, drain (or fail) queued ones, emit
        a final metrics row.  Returns lifetime stats."""
        self.batcher.close()
        if not drain:
            self.batcher.abort_pending(ServerClosed("server stopped"))
        if self._worker is not None:
            self._worker.join(timeout=60)
            self._worker = None
        # whatever is STILL queued (never started, or the join timed out on a
        # wedged worker) fails promptly instead of hanging its clients until
        # their own result() timeouts
        self.batcher.abort_pending(ServerClosed("server stopped"))
        if self.watcher is not None:
            self.watcher.stop()
            if self._owns_checkpointer:
                self._owns_checkpointer = False  # idempotent double-stop
                self.watcher.ckpt.close()
        if self.obs_http is not None:
            self.obs_http.stop()
        self.metrics.emit(final=True)
        if self.obs_relay is not None:
            self.obs_relay.close()  # drains the final row before the close
            self.obs_relay = None
        if self.metrics.logger is not None:
            self.metrics.logger.close()
        return self.metrics.stats()

    def __enter__(self) -> "PolicyServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ------------------------------------------------------------ client API
    def submit(self, obs: np.ndarray) -> ServeFuture:
        """Enqueue one observation [H, W, C] uint8; returns a future.
        Raises ServerOverloaded when the queue is at its bound (shed) and
        ServerClosed after stop().  Shape/dtype are validated HERE, in the
        caller's thread — a malformed observation must fail its own client,
        never reach the worker's batch assembly."""
        arr = np.asarray(obs)
        if tuple(arr.shape) != self._obs_shape:
            raise ValueError(
                f"observation shape {tuple(arr.shape)} != served {self._obs_shape}"
            )
        if arr.dtype != np.uint8:
            # silent uint8 truncation would turn normalized float frames
            # into all-zero pixels and confidently wrong actions
            raise TypeError(f"observations must be uint8 frames, got {arr.dtype}")
        return self.batcher.submit(arr)

    def try_submit(self, obs: np.ndarray) -> Optional[ServeFuture]:
        """submit() that returns None on a full queue instead of recording a
        shed — for the fleet router's multi-engine dispatch probes (the
        router owns the shed story; see MicroBatcher.try_submit)."""
        arr = np.asarray(obs)
        if tuple(arr.shape) != self._obs_shape:
            raise ValueError(
                f"observation shape {tuple(arr.shape)} != served {self._obs_shape}"
            )
        if arr.dtype != np.uint8:
            raise TypeError(f"observations must be uint8 frames, got {arr.dtype}")
        return self.batcher.try_submit(arr)

    def act(self, obs: np.ndarray, timeout: Optional[float] = 30.0) -> int:
        """Blocking convenience: one observation in, one action out."""
        action, _ = self.act_values(obs, timeout)
        return action

    def act_values(
        self, obs: np.ndarray, timeout: Optional[float] = 30.0
    ) -> Tuple[int, np.ndarray]:
        """Blocking act returning (action, expected Q per action [A]).
        A timed-out request is CANCELLED before the TimeoutError propagates:
        this client has given up, so the batcher must not pad, dispatch and
        fulfil its dead slot (counted as serve_cancelled_total)."""
        fut = self.submit(obs)
        try:
            return fut.result(timeout)
        except TimeoutError:
            fut.cancel()
            raise

    def reload(self, step: Optional[int] = None, force: bool = False) -> Dict[str, Any]:
        """Explicit hot-swap from the watched checkpoint dir."""
        if self.watcher is None:
            raise RuntimeError("server was built without a checkpointer")
        return self.watcher.reload(step=step, force=force)

    def load_params(self, params: Any) -> int:
        """Direct hot-swap from an in-memory params tree (the learner-process
        colocated path: no checkpoint round-trip)."""
        version = self.engine.load_params(params)
        self.metrics.record_swap(ok=True, params_version=version, source="direct")
        return version

    def healthz(self) -> Dict[str, Any]:
        """Live status for /healthz: failing = the worker thread died under a
        started server (nothing will drain the queue); degraded = shedding in
        the current window or the queue is within 20% of its shed bound."""
        snap = self.metrics.snapshot()
        depth = self.batcher.depth()
        worker_alive = self._worker is not None and self._worker.is_alive()
        status = "ok"
        if snap.get("shed", 0) > 0 or depth >= 0.8 * self.cfg.serve_queue_bound:
            status = "degraded"
        if self._started and not worker_alive:
            status = "failing"
        return {
            "status": status,
            "queue_depth": depth,
            "worker_alive": worker_alive,
            "params_version": self.engine.params_version,
            # serving staleness, externally monitorable (the serving mirror
            # of the actor-side weight_version_lag gauge): which weight
            # version is live and how long since it changed
            "weights_version": self.engine.params_version,
            "weights_age_s": round(self.engine.weights_age_s(), 3),
            "weights_step": None if self.watcher is None
            else self.watcher.last_step,
            # quantized-inference status (docs/SERVING.md): which numeric
            # path is live and the last gate's agreement
            **self.engine.quant_state(),
            **snap,
        }

    def stats(self) -> Dict[str, Any]:
        return {
            "queue_depth": self.batcher.depth(),
            "params_version": self.engine.params_version,
            "compiled_executables": self.engine.compiled_executables(),
            "buckets": self.engine.buckets,
            **self.engine.quant_state(),
            **self.metrics.stats(),
        }

    # ------------------------------------------------------------ worker loop
    def _serve_loop(self) -> None:
        last_emit = time.monotonic()
        # idle timeout = metrics interval: take() returns [] on a quiet
        # queue so the heartbeat row below still fires with zero traffic
        # (a consumer must be able to tell "up, idle" from "dead")
        idle_s = self._metrics_interval_s or None
        while True:
            batch = self.batcher.take(idle_timeout_s=idle_s)
            if batch is None:  # closed and drained
                break
            if batch:
                try:
                    obs = np.stack([f.obs for f in batch])
                    actions, qs = self.engine.infer(obs)
                except Exception as e:  # fail the batch, keep serving
                    for fut in batch:
                        fut.set_error(e)
                else:
                    for i, fut in enumerate(batch):
                        fut.set_result(int(actions[i]), qs[i])
                        self.metrics.record_latency_ms(fut.latency_ms)
            now = time.monotonic()
            if self._metrics_interval_s and now - last_emit >= self._metrics_interval_s:
                last_emit = now
                try:
                    self.metrics.emit(queue_depth=self.batcher.depth())
                except Exception:  # a metrics I/O failure (disk full on the
                    pass           # JSONL path) must never kill the worker
