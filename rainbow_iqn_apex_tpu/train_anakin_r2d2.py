"""Fused R2D2 Anakin: recurrent actor + env + stored-state sequence replay +
sequence learner, ALL inside one scanned XLA graph.

The recurrent twin of train_anakin.train_anakin_fused — same Podracer/Anakin
topology (the reference's actor+learner+Redis loop, SURVEY.md §3.1-3.2,
collapsed into a single jitted program), with the transition ring replaced by
the HBM sequence ring (replay/device_sequence.py) and the frame-stack actor
replaced by the LSTM actor threading (c, h) through the scan carry.

Semantics pinned to the host R2D2 trainer (train_r2d2.py):
  - the actor sees frame-stacked input AND an LSTM; the replay stores single
    frames + the PRE-act LSTM state of each step (stored-state replay);
  - LSTM state zero-resets on terminal OR truncation (keep mask);
  - learn cadence: one sequence learn step per frames_per_learn * r2d2_seq_len
    env frames — the same per-transition reuse as the feedforward path —
    expressed statically as `period` ticks per step (or k steps per tick
    when lanes exceed that frame budget);
  - warm gate: filled >= max(learn_start // seq_total, 8) sequences, the
    host trainer's learn_start_seqs rule (and the contract
    build_device_r2d2_learn documents).

Multi-device (`--learner-devices N`): env lanes, LSTM lanes and the sequence
ring shard over a dp mesh — per-shard rings under shard_map (sequence
emission is data-dependent, so each shard owns its cursors), per-shard draws
with psum/pmax-corrected IS weights, GSPMD gradient all-reduce
(replay/device_sequence.build_device_r2d2_learn_sharded).
"""

from __future__ import annotations

import collections
import functools
import os
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from rainbow_iqn_apex_tpu.config import Config
from rainbow_iqn_apex_tpu.obs import RunObs
from rainbow_iqn_apex_tpu.ops.r2d2 import (
    build_r2d2_act_step,
    init_r2d2_state,
)
from rainbow_iqn_apex_tpu.parallel.multihost import shift_stack
from rainbow_iqn_apex_tpu.replay.device_sequence import (
    DeviceSeqState,
    DeviceSequenceReplay,
    build_device_r2d2_learn,
)
from rainbow_iqn_apex_tpu.train import priority_beta
from rainbow_iqn_apex_tpu.utils.checkpoint import Checkpointer, maybe_resume
from rainbow_iqn_apex_tpu.utils.logging import MetricsLogger


def _seq_geometry(cfg: Config):
    """(seq_total, stride, capacity, learn_start_seqs) — host-trainer parity
    (train_r2d2.train_r2d2)."""
    seq_total = cfg.r2d2_burn_in + cfg.r2d2_seq_len
    stride = max(seq_total - cfg.r2d2_overlap, 1)
    capacity = max(cfg.memory_capacity // seq_total, 64)
    learn_start_seqs = max(cfg.learn_start // seq_total, 8)
    return seq_total, stride, capacity, learn_start_seqs


def _learn_cadence(cfg: Config):
    """Static (period_ticks, learns_per_tick) for the in-graph cadence:
    one learn step per frames_per_learn * r2d2_seq_len env frames."""
    fps = cfg.frames_per_learn * cfg.r2d2_seq_len
    lanes = cfg.num_envs_per_actor
    if fps % lanes == 0:
        return fps // lanes, 1
    if lanes % fps == 0:
        return 1, lanes // fps
    # suggest the nearest valid lane counts (ADVICE r3: the reference's
    # cadence is a free parameter; make the constraint cheap to satisfy)
    valid = sorted(
        {d for d in range(1, max(fps, lanes) * 2 + 1)
         if fps % d == 0 or d % fps == 0}
    )
    below = max((d for d in valid if d < lanes), default=None)
    above = min((d for d in valid if d > lanes), default=None)
    near = " or ".join(str(d) for d in (below, above) if d is not None)
    raise ValueError(
        f"fused R2D2 anakin needs lanes ({lanes}) and frames_per_learn * "
        f"r2d2_seq_len ({fps}) to divide one another — the learn cadence "
        f"is compiled into the graph.  Nearest valid --num-envs-per-actor: "
        f"{near}"
    )


def build_fused_r2d2_segment(cfg: Config, game, replay: DeviceSequenceReplay,
                             learn_fn, append_fn=None):
    """Jitted (carry, key) -> (carry, outs) scanning anakin_segment_ticks of
    shift_stack -> recurrent act -> env.step -> sequence append -> gated
    learn.  carry = (ts, ss, env_states, ep_returns, stack, frame, keep,
    lstm_c, lstm_h, frames); outs = per-tick (ep_return [L], loss/q_mean/
    grad_norm [learns_per_tick], NaN when cold or off-cadence).

    `append_fn` defaults to replay.append; the sharded path passes the
    shard_map'd build_sharded_seq_append so each device's lanes emit into
    their own ring."""
    from rainbow_iqn_apex_tpu.envs.device_games import batched_reset_step

    lanes = cfg.num_envs_per_actor
    period, lpt = _learn_cadence(cfg)
    _, _, _, learn_start_seqs = _seq_geometry(cfg)
    act_fn = build_r2d2_act_step(cfg, game.num_actions, use_noise=True)
    env_step = batched_reset_step(game)
    append = append_fn or replay.append
    bw = cfg.priority_weight

    def tick(carry, k):
        ts, ss, env_s, ep, stack, frame, keep, c, h, frames = carry
        ka, ks, kl = jax.random.split(k, 3)
        pre_c, pre_h = c, h  # stored-state replay keeps the PRE-act state
        stack = shift_stack(stack, frame, keep)
        actions, _q, (c, h) = act_fn(ts.params, stack, (c, h), ka)
        env_s, ep, nframe, reward, term, trunc, out_ret = env_step(
            env_s, ep, actions, ks
        )
        ss = append(ss, frame, actions, reward, term, trunc, pre_c, pre_h)
        frames = frames + lanes

        # warm gate (sum/min are shard-aware: filled is [n_dev] when the
        # ring is stacked-sharded, a scalar otherwise) + static cadence
        warm = (jnp.sum(ss.filled) >= learn_start_seqs) & (
            jnp.min(ss.filled) >= 1
        )
        due = (frames // lanes) % period == 0
        beta = jnp.float32(
            bw + (1.0 - bw) * jnp.minimum(frames / float(cfg.t_max), 1.0)
        )

        def do_learn(args):
            ts, ss = args

            def one(cr, kk):
                ts, ss = cr
                ts, ss, info = learn_fn(ts, ss, kk, beta)
                return (ts, ss), (info["loss"], info["q_mean"],
                                  info["grad_norm"])

            (ts, ss), infos = jax.lax.scan(
                one, (ts, ss), jax.random.split(kl, lpt)
            )
            return ts, ss, infos

        def no_learn(args):
            ts, ss = args
            nanv = jnp.full((lpt,), jnp.nan, jnp.float32)
            return ts, ss, (nanv, nanv, nanv)

        ts, ss, infos = jax.lax.cond(warm & due, do_learn, no_learn, (ts, ss))

        cut_keep = (~(term | trunc)).astype(jnp.uint8)
        kf = cut_keep.astype(jnp.float32)[:, None]
        c, h = c * kf, h * kf  # LSTM zero-reset on episode cut
        out = (out_ret, infos[0], infos[1], infos[2])
        return (ts, ss, env_s, ep, stack, nframe, cut_keep, c, h, frames), out

    @functools.partial(jax.jit, donate_argnums=(0,))
    def segment(carry, key):
        return jax.lax.scan(
            tick, carry, jax.random.split(key, cfg.anakin_segment_ticks)
        )

    return segment


def init_fused_r2d2_carry(cfg: Config, game, ts, ss, key, frames: int = 0):
    from rainbow_iqn_apex_tpu.envs.device_games import batched_init

    lanes = cfg.num_envs_per_actor
    h, w = game.frame_shape
    env_s = batched_init(game, key, lanes)
    ep = jnp.zeros(lanes)
    stack = jnp.zeros((lanes, h, w, cfg.history_length), jnp.uint8)
    frame = jax.vmap(game.render)(env_s)
    keep = jnp.ones(lanes, jnp.uint8)
    # two distinct buffers: the segment donates its carry, and donating one
    # array twice (aliased c == h) is a runtime error
    c = jnp.zeros((lanes, cfg.lstm_size), jnp.float32)
    h = jnp.zeros((lanes, cfg.lstm_size), jnp.float32)
    return (ts, ss, env_s, ep, stack, frame, keep, c, h, jnp.int32(frames))


def build_fused_r2d2_eval(cfg: Config, game, episodes: int,
                          max_ticks: int = 1024):
    """In-graph recurrent evaluation: greedy LSTM lanes on the shared rollout
    core, state zero-reset on cut via the rollout's keep mask (the recurrent
    analog of train_anakin.build_fused_eval)."""
    from rainbow_iqn_apex_tpu.envs.device_games import build_rollout

    act_fn = build_r2d2_act_step(cfg, game.num_actions,
                                 use_noise=cfg.eval_noisy)

    def action_fn(params, states, stack, key, lstm):
        a, _q, lstm = act_fn(params, stack, lstm, key)
        return a, lstm

    def actor_init(n):
        z = jnp.zeros((n, cfg.lstm_size), jnp.float32)
        return (z, z)

    return build_rollout(game, action_fn, episodes, max_ticks,
                         history=cfg.history_length, actor_init=actor_init)


def _replay_snapshot_path(cfg: Config) -> str:
    return os.path.join(cfg.checkpoint_dir, cfg.run_id, "replay_anakin_r2d2.npz")


def _save_replay(cfg: Config, ss: DeviceSeqState) -> None:
    if not cfg.snapshot_replay:
        return
    from rainbow_iqn_apex_tpu.replay import snapshot_io

    host = jax.device_get(ss)
    snapshot_io.atomic_savez(
        _replay_snapshot_path(cfg),
        **{f: getattr(host, f) for f in DeviceSeqState._fields},
    )


def _maybe_restore_replay(cfg: Config, ss: DeviceSeqState) -> DeviceSeqState:
    path = _replay_snapshot_path(cfg)
    if not (cfg.snapshot_replay and os.path.exists(path)):
        return ss
    from rainbow_iqn_apex_tpu.replay import snapshot_io

    z = snapshot_io.load(path)
    if tuple(z["frames"].shape) != tuple(ss.frames.shape):
        return ss  # geometry change: degrade to cold replay (host-path rule)
    return DeviceSeqState(
        **{f: jnp.asarray(z[f]) for f in DeviceSeqState._fields}
    )


def train_anakin_r2d2(cfg: Config,
                      max_frames: Optional[int] = None) -> Dict[str, Any]:
    """R2D2 Anakin: HBM sequence replay either fully fused (jaxgame:* envs,
    the flagship) or host-fed (any Env — the lag-one loop of
    train_anakin.train_anakin with an LSTM actor)."""
    from rainbow_iqn_apex_tpu.envs.device_games import (
        make_device_game,
        tick_budget,
    )

    if cfg.replay_ratio > 1:
        raise ValueError(
            "replay_ratio > 1 (clipped replay reuse) is implemented for the "
            "single-process and apex IQN loops; the fused anakin R2D2 "
            "learner rejects it (ROADMAP follow-up)")
    if not (cfg.fused_env and cfg.env_id.startswith("jaxgame:")):
        return _train_anakin_r2d2_hostfed(cfg, max_frames)
    total_frames = max_frames or cfg.t_max
    lanes = cfg.num_envs_per_actor
    T = cfg.anakin_segment_ticks
    game_name = cfg.env_id.split(":", 1)[1]
    game = make_device_game(game_name)
    h, w = game.frame_shape
    seq_total, stride, capacity, _ = _seq_geometry(cfg)
    _learn_cadence(cfg)  # validate divisibility before building anything

    key = jax.random.PRNGKey(cfg.seed)
    key, k_init, k_env = jax.random.split(key, 3)
    ts = init_r2d2_state(cfg, game.num_actions, k_init, frame_shape=(h, w))

    n_dev = cfg.learner_devices if cfg.learner_devices > 0 else len(jax.devices())
    if n_dev > 1:
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from rainbow_iqn_apex_tpu.replay.device_sequence import (
            build_device_r2d2_learn_sharded,
            build_sharded_seq_append,
            device_seq_shardings,
            stack_seq_shards,
        )

        if lanes % n_dev or cfg.batch_size % n_dev or capacity % n_dev:
            raise ValueError(
                f"fused R2D2 anakin over {n_dev} devices needs lanes "
                f"({lanes}), batch ({cfg.batch_size}) and sequence capacity "
                f"({capacity}) divisible by the device count"
            )
        mesh = Mesh(np.array(jax.devices()[:n_dev]), ("dp",))
        local_replay = DeviceSequenceReplay(
            capacity=capacity // n_dev, seq_len=seq_total,
            frame_shape=(h, w), lstm_size=cfg.lstm_size,
            lanes=lanes // n_dev, stride=stride,
            priority_exponent=cfg.priority_exponent,
            priority_eps=cfg.priority_eps,
        )
        replay = local_replay
        learn_fn = build_device_r2d2_learn_sharded(
            cfg, game.num_actions, local_replay, mesh
        )
        append_fn = build_sharded_seq_append(local_replay, mesh)
        ss0 = jax.device_put(
            stack_seq_shards(local_replay.init_state(), n_dev),
            device_seq_shardings(mesh),
        )
        _lane = NamedSharding(mesh, P("dp"))
        _rep = NamedSharding(mesh, P())

        def place(carry):
            ts, ss, env_s, ep, stack, frame, keep, c, hh, frames = carry
            lane_tree = jax.tree.map(
                lambda x: jax.device_put(x, _lane),
                (env_s, ep, stack, frame, keep, c, hh),
            )
            return (
                jax.device_put(ts, _rep),
                jax.device_put(ss, device_seq_shardings(mesh)),
                *lane_tree,
                jax.device_put(frames, _rep),
            )
    else:
        replay = DeviceSequenceReplay(
            capacity=capacity, seq_len=seq_total, frame_shape=(h, w),
            lstm_size=cfg.lstm_size, lanes=lanes, stride=stride,
            priority_exponent=cfg.priority_exponent,
            priority_eps=cfg.priority_eps,
        )
        learn_fn = build_device_r2d2_learn(cfg, game.num_actions, replay)
        append_fn = None
        ss0 = replay.init_state()
        place = lambda carry: carry  # noqa: E731

    segment = build_fused_r2d2_segment(cfg, game, replay, learn_fn, append_fn)

    run_dir = os.path.join(cfg.results_dir, cfg.run_id)
    metrics = MetricsLogger(os.path.join(run_dir, "metrics.jsonl"), cfg.run_id)
    ckpt = Checkpointer(os.path.join(cfg.checkpoint_dir, cfg.run_id))
    obs_run = RunObs(cfg, metrics, role="learner")

    frames = 0
    ss = ss0
    restored = maybe_resume(cfg, ckpt, ts)
    if restored is not None:
        ts, extra, _ = restored
        frames = int(extra.get("frames", 0))
        ss = _maybe_restore_replay(cfg, ss)
        metrics.log("resume", step=int(ts.step), frames=frames)
    learn_steps = int(ts.step)

    carry = place(init_fused_r2d2_carry(cfg, game, ts, ss, k_env, frames))

    eval_fn = build_fused_r2d2_eval(
        cfg, game, cfg.eval_episodes, max_ticks=tick_budget(game_name, 1024)
    )

    def run_eval(params, step_no: int) -> Dict[str, Any]:
        from rainbow_iqn_apex_tpu.train_anakin import fused_eval_scores

        k = jax.random.fold_in(jax.random.PRNGKey(cfg.seed + 977), step_no)
        return fused_eval_scores(eval_fn, params, k)

    returns: collections.deque = collections.deque(maxlen=100)

    def crossed(interval: int, before: int, after: int) -> bool:
        return interval > 0 and before // interval != after // interval

    try:
        while frames < total_frames:
            key, k = jax.random.split(key)
            with obs_run.span("segment", ticks=T):
                carry, (out_ret, loss, q_mean, grad_norm) = segment(carry, k)
                ts, ss = carry[0], carry[1]
                frames += T * lanes
                prev_steps = learn_steps
                learn_steps = int(ts.step)
            obs_run.after_learn_step(learn_steps)
            for r in np.asarray(out_ret)[~np.isnan(np.asarray(out_ret))]:
                returns.append(float(r))

            if crossed(cfg.metrics_interval, prev_steps, learn_steps):
                l = np.asarray(loss)
                metrics.log(
                    "learn",
                    step=learn_steps,
                    frames=frames,
                    fps=metrics.fps(frames),
                    loss=float(np.nanmean(l)) if np.any(~np.isnan(l)) else float("nan"),
                    q_mean=float(np.nanmean(np.asarray(q_mean)))
                    if np.any(~np.isnan(np.asarray(q_mean))) else float("nan"),
                    grad_norm=float(np.nanmean(np.asarray(grad_norm)))
                    if np.any(~np.isnan(np.asarray(grad_norm))) else float("nan"),
                    mean_return=float(np.mean(returns)) if returns else float("nan"),
                )
                obs_run.periodic(learn_steps, frames)
            if crossed(cfg.eval_interval, prev_steps, learn_steps):
                metrics.log("eval", step=learn_steps,
                            **run_eval(carry[0].params, learn_steps))
            if crossed(cfg.checkpoint_interval, prev_steps, learn_steps):
                ckpt.save(learn_steps, ts, {"frames": frames})
                _save_replay(cfg, ss)

    finally:
        obs_run.close(learn_steps, frames)
    final_eval = run_eval(carry[0].params, learn_steps)
    metrics.log("eval", step=learn_steps, **final_eval)
    ckpt.save(learn_steps, ts, {"frames": frames})
    _save_replay(cfg, ss)
    ckpt.wait()
    metrics.close()
    return {
        "frames": frames,
        "learn_steps": learn_steps,
        "train_return_mean": float(np.mean(returns)) if returns else float("nan"),
        **{f"eval_{k}": v for k, v in final_eval.items()},
    }


def _train_anakin_r2d2_hostfed(cfg: Config,
                               max_frames: Optional[int] = None) -> Dict[str, Any]:
    """Host-fed R2D2 Anakin: env on host, everything else in HBM — sequence
    ring, builders, LSTM state and frame stack all device-resident across
    ticks; per tick the host ships one [L, H, W] frame tensor and reads back
    actions (the exact lag-one staging of train_anakin.train_anakin, with
    the recurrent actor).  This is the trainer real ALE Atari will use once
    ROMs exist (SURVEY.md §2 native-dep row: ALE stays host-side)."""
    from rainbow_iqn_apex_tpu.agents.agent import put_frames
    from rainbow_iqn_apex_tpu.envs import make_vector_env

    total_frames = max_frames or cfg.t_max
    lanes = cfg.num_envs_per_actor
    env = make_vector_env(cfg.env_id, lanes, seed=cfg.seed)
    h, w = env.frame_shape
    seq_total, stride, capacity, learn_start_seqs = _seq_geometry(cfg)
    replay = DeviceSequenceReplay(
        capacity=capacity, seq_len=seq_total, frame_shape=(h, w),
        lstm_size=cfg.lstm_size, lanes=lanes, stride=stride,
        priority_exponent=cfg.priority_exponent,
        priority_eps=cfg.priority_eps,
    )
    key = jax.random.PRNGKey(cfg.seed)
    key, k_init = jax.random.split(key)
    ts = init_r2d2_state(cfg, env.num_actions, k_init, frame_shape=(h, w))
    act_fn = build_r2d2_act_step(cfg, env.num_actions, use_noise=True)

    @functools.partial(jax.jit, donate_argnums=(1, 2, 3))
    def act_append(params, stack, ss, lstm, frame, keep, prev, key):
        """Append LAST tick's completed transition (lag-one: reward/cut are
        only known after env.step), zero-reset cut lanes' stack + LSTM, act.
        Returns the pre-act LSTM state for the NEXT append (stored-state
        replay keeps the state the actor had BEFORE seeing each frame)."""
        if prev is not None:
            ss = replay.append(ss, *prev)
        stack = shift_stack(stack, frame, keep)
        kf = keep.astype(jnp.float32)[:, None]
        c, h2 = lstm[0] * kf, lstm[1] * kf
        pre = (c, h2)
        a, _q, lstm = act_fn(params, stack, (c, h2), key)
        return a, stack, ss, lstm, pre

    learn = jax.jit(
        build_device_r2d2_learn(cfg, env.num_actions, replay),
        donate_argnums=(0, 1),
    )

    run_dir = os.path.join(cfg.results_dir, cfg.run_id)
    metrics = MetricsLogger(os.path.join(run_dir, "metrics.jsonl"), cfg.run_id)
    ckpt = Checkpointer(os.path.join(cfg.checkpoint_dir, cfg.run_id))
    obs_run = RunObs(cfg, metrics, role="learner")

    frames = 0
    ss = replay.init_state()
    restored = maybe_resume(cfg, ckpt, ts)
    if restored is not None:
        ts, extra, _ = restored
        frames = int(extra.get("frames", 0))
        ss = _maybe_restore_replay(cfg, ss)
        metrics.log("resume", step=int(ts.step), frames=frames)
    learn_steps = int(ts.step)

    stack = jnp.zeros((lanes, h, w, cfg.history_length), jnp.uint8)
    z1 = jnp.zeros((lanes, cfg.lstm_size), jnp.float32)
    z2 = jnp.zeros((lanes, cfg.lstm_size), jnp.float32)
    lstm = (z1, z2)
    obs = env.reset()
    prev_cuts = np.zeros(lanes, bool)
    prev = None
    returns: collections.deque = collections.deque(maxlen=100)
    device = jax.devices()[0]
    frames_per_step = cfg.frames_per_learn * cfg.r2d2_seq_len
    warm = False  # latches: filled is monotone, so stop syncing once open

    # one eval agent for the whole run (rebuilding it per eval would redo
    # init + jit of the act step every interval)
    from rainbow_iqn_apex_tpu.train_r2d2 import R2D2Agent, evaluate_r2d2

    eval_agent = R2D2Agent(cfg, env.num_actions, env.frame_shape,
                           jax.random.PRNGKey(cfg.seed + 31), train=False)

    def run_eval(ts):
        eval_agent.state = ts
        return evaluate_r2d2(cfg, eval_agent, seed=cfg.seed + 977)

    try:
        while frames < total_frames:
            frame_d = put_frames(obs)
            keep_d = jax.device_put((~prev_cuts).astype(np.uint8), device)
            key, k = jax.random.split(key)
            with obs_run.span("act_append"):
                actions_d, stack, ss, lstm, pre = act_append(
                    ts.params, stack, ss, lstm, frame_d, keep_d, prev, k
                )
                actions = np.asarray(actions_d)
            new_obs, rewards, terminals, truncs, ep_returns = env.step(actions)
            prev = (
                frame_d,
                actions_d,
                jax.device_put(rewards.astype(np.float32), device),
                jax.device_put(terminals, device),
                jax.device_put(truncs, device),
                pre[0],
                pre[1],
            )
            prev_cuts = terminals | truncs
            obs = new_obs
            frames += lanes
            for r in ep_returns[~np.isnan(ep_returns)]:
                returns.append(float(r))

            # warm gate on the ring's own sequence count (one scalar readback
            # per tick until it opens — the fused path avoids even this)
            if not warm and int(jax.device_get(ss.filled)) >= learn_start_seqs:
                warm = True
                # cadence counts from the warm-open point: without this, the
                # first tick would owe ~learn_start/frames_per_step catch-up
                # steps against a minimally-filled ring (heavy early sample
                # reuse, ADVICE r3) — the fused path's static cadence has no
                # such burst, and A/B parity with it matters more than parity
                # with train_r2d2's cold-start spike.  Both counters are
                # latched so a resumed run (restored frames/learn_steps) keeps
                # its cadence instead of stalling against the old totals.
                warm_open_frames = frames
                warm_open_steps = learn_steps
            if warm:
                steps_due = ((frames - warm_open_frames) // frames_per_step
                             - (learn_steps - warm_open_steps))
                for _ in range(max(steps_due, 0)):
                    key, k = jax.random.split(key)
                    with obs_run.span("learn_step"):
                        ts, ss, info = learn(
                            ts, ss, k, jnp.float32(priority_beta(cfg, frames))
                        )
                    learn_steps += 1
                    # no block_on (see train_anakin.py): keep the dispatch async
                    obs_run.after_learn_step(learn_steps)
                    if learn_steps % cfg.metrics_interval == 0:
                        metrics.log(
                            "learn", step=learn_steps, frames=frames,
                            fps=metrics.fps(frames), loss=float(info["loss"]),
                            q_mean=float(info["q_mean"]),
                            grad_norm=float(info["grad_norm"]),
                            mean_return=float(np.mean(returns))
                            if returns else float("nan"),
                        )
                        obs_run.periodic(learn_steps, frames)
                    if cfg.eval_interval and learn_steps % cfg.eval_interval == 0:
                        metrics.log("eval", step=learn_steps, **run_eval(ts))
                    if (cfg.checkpoint_interval
                            and learn_steps % cfg.checkpoint_interval == 0):
                        ckpt.save(learn_steps, ts, {"frames": frames})
                        _save_replay(cfg, ss)

    finally:
        obs_run.close(learn_steps, frames)
    final_eval = run_eval(ts)
    metrics.log("eval", step=learn_steps, **final_eval)
    ckpt.save(learn_steps, ts, {"frames": frames})
    _save_replay(cfg, ss)
    ckpt.wait()
    metrics.close()
    return {
        "frames": frames,
        "learn_steps": learn_steps,
        "train_return_mean": float(np.mean(returns)) if returns else float("nan"),
        **{f"eval_{k}": v for k, v in final_eval.items()},
    }
