"""Vectorised sum-tree (segment tree) for proportional prioritized replay.

Parity: reference `rainbowiqn/memory.py` `SegmentTree` (SURVEY.md §2 row 5;
algorithm: Schaul et al. arXiv:1511.05952).  The reference walks the tree one
node at a time in Python; at the build's target throughput that pointer-chase
is the bottleneck (SURVEY.md §7 "hard parts"), so this implementation stores
the tree as one flat array and performs *batched* updates and *batched*
stratified sampling — every tree level is one vectorised NumPy op over the
whole batch.  A C++ core (`native.py`) implements the same layout for the
hot path; this module is the reference implementation and fallback.

Layout: classic implicit binary heap over a power-of-two leaf span.
  tree[1] = root (total priority); children of i are 2i, 2i+1;
  leaves occupy [span, span + capacity).
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np


class SumTree:
    def __init__(self, capacity: int):
        if capacity <= 0:
            raise ValueError("capacity must be positive")
        self.capacity = capacity
        self.span = 1 << (capacity - 1).bit_length()  # next power of two
        self.tree = np.zeros(2 * self.span, dtype=np.float64)
        # float64: at 1e6 leaves, fp32 partial sums drift enough to break the
        # invariant root == sum(leaves) under millions of incremental updates.

    # ------------------------------------------------------------------ totals
    @property
    def total(self) -> float:
        return float(self.tree[1])

    def max_leaf(self, filled: Optional[int] = None, lanes: int = 1) -> float:
        """Max leaf priority, clamped to WRITTEN slots when the caller's
        ring geometry is given: ``filled`` is the per-lane written count and
        ``lanes`` the lane count of a multi-lane ring (lane ``l`` owns the
        contiguous leaf block ``[l*seg, l*seg + seg)``, written prefix
        ``filled``).  Without the clamp the scan covers never-written slots
        too — a restored/partially rebuilt tree whose unwritten span carries
        residue would leak it into the fresh-item default priority
        (``max_priority`` re-seeding after restore/readmission)."""
        leaves = self.tree[self.span : self.span + self.capacity]
        if filled is not None:
            seg = self.capacity // max(int(lanes), 1)
            filled = min(int(filled), seg)
            mask = (np.arange(self.capacity) % max(seg, 1)) < filled
            leaves = leaves[mask]
        return float(leaves.max()) if leaves.size else 0.0

    def min_leaf_nonzero(self) -> float:
        leaves = self.tree[self.span : self.span + self.capacity]
        nz = leaves[leaves > 0]
        return float(nz.min()) if nz.size else 0.0

    def get(self, idx: np.ndarray) -> np.ndarray:
        """Leaf priorities at data indices ``idx``."""
        return self.tree[self.span + np.asarray(idx)]

    # ----------------------------------------------------------------- updates
    def set(self, idx: np.ndarray, priority: np.ndarray) -> None:
        """Batched leaf assignment + ancestor fix-up, one op per tree level.

        Duplicate indices are allowed; the LAST write wins (matching the
        sequential semantics of the reference's per-item loop).
        """
        idx = np.asarray(idx, dtype=np.int64).ravel()
        priority = np.broadcast_to(
            np.asarray(priority, dtype=np.float64).ravel(), idx.shape
        )
        if idx.size == 0:
            return
        if np.any(priority < 0) or not np.all(np.isfinite(priority)):
            raise ValueError("priorities must be finite and non-negative")

        # Resolve duplicates: keep the last occurrence of each index.
        if idx.size > 1:
            _, last_pos = np.unique(idx[::-1], return_index=True)
            keep = idx.size - 1 - last_pos
            idx, priority = idx[keep], priority[keep]

        nodes = self.span + idx
        delta = priority - self.tree[nodes]
        self.tree[nodes] = priority
        nodes >>= 1
        while nodes[0] >= 1:
            # Siblings updated in the same batch collapse via add.at (sums
            # duplicate node contributions), keeping ancestors exact.
            np.add.at(self.tree, nodes, delta)
            nodes >>= 1
        # note: nodes[0] reaches 0 only after the root (1) was updated.

    # ---------------------------------------------------------------- sampling
    def find_prefix(self, mass: np.ndarray) -> np.ndarray:
        """Batched prefix-sum descent: for each mass m in [0, total), find the
        leaf i with  sum(leaves[:i]) <= m < sum(leaves[:i+1]).

        One vectorised step per tree level (log2(span) steps total).
        """
        mass = np.asarray(mass, dtype=np.float64).copy()
        node = np.ones_like(mass, dtype=np.int64)
        while node[0] < self.span:  # all nodes are on the same level
            node <<= 1  # left child
            left = self.tree[node]
            go_right = mass >= left
            mass -= np.where(go_right, left, 0.0)
            node += go_right
        leaf = node - self.span
        # Guard against fp edge-fall onto a zero-priority / out-of-range leaf.
        return np.minimum(leaf, self.capacity - 1)

    def sample_stratified(
        self, batch_size: int, rng: np.random.Generator
    ) -> Tuple[np.ndarray, np.ndarray]:
        """PER stratified sampling: one uniform draw per equal slice of total
        mass (reference behaviour, SURVEY §2 row 5). Returns (idx, prob)."""
        total = self.total
        if total <= 0:
            raise ValueError("cannot sample from an empty tree")
        seg = total / batch_size
        mass = (np.arange(batch_size) + rng.random(batch_size)) * seg
        idx = self.find_prefix(mass)
        prob = self.get(idx) / total
        return idx, prob
