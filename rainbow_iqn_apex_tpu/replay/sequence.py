"""Stored-state sequence replay for R2D2.

Parity: the reference's R2D2 stretch config (BASELINE.json:10; SURVEY.md §5
"long-context": sequence replay is replay-format work — stored LSTM state +
burn-in — not sequence-parallel compute).  Design per Kapturowski et al.:

- actors chop each lane's episode stream into fixed-length sequences of
  L = burn_in + seq_len steps, adjacent sequences overlapping by L - stride;
- each sequence records the actor's LSTM state at its first step (the
  "stored state" that seeds burn-in at training time) — exact for overlapped
  windows too, via a per-step state history;
- sequences never mix episodes: a terminal OR truncation inside the window
  ends the valid region and the remainder is zero-padded with valid=False.
  Two-channel cut semantics (mirroring the frame replay,
  replay/buffer.py): both channels cut the stream, but only true terminals
  are stored in `done` — a time-limit truncation leaves done=False, and the
  learn step (ops/r2d2.py) masks out steps whose bootstrap would need data
  beyond the cut instead of teaching V=0 there;
- a sum-tree prioritizes whole sequences (max-priority on insert, eta-mix
  write-back from the learner).

Storage is sequence-major NumPy: frames are duplicated across overlapping
windows (factor ~L/stride) in exchange for contiguous [B, L] gathers that
feed the TPU directly — the dedup trick of the frame replay doesn't pay here
because the LSTM needs contiguous time anyway.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Optional, Tuple

import numpy as np

from rainbow_iqn_apex_tpu.replay.sumtree import SumTree
from rainbow_iqn_apex_tpu.utils import hostsync


@dataclasses.dataclass
class SequenceSample:
    idx: np.ndarray  # [B] sequence slot ids
    obs: np.ndarray  # [B, L, H, W, 1] uint8
    action: np.ndarray  # [B, L] int32
    reward: np.ndarray  # [B, L] f32
    done: np.ndarray  # [B, L] bool
    valid: np.ndarray  # [B, L] bool
    init_c: np.ndarray  # [B, lstm] f32
    init_h: np.ndarray  # [B, lstm] f32
    weight: np.ndarray  # [B] f32
    prob: np.ndarray = None  # [B] f64 — local sample probability (for the
    # multi-host global IS-weight derivation, mirroring SampledBatch.prob)


class SequenceReplay:
    """Prioritized ring of fixed-length sequences with stored LSTM states."""

    def __init__(
        self,
        capacity: int,  # number of sequences
        seq_len: int,  # L = burn_in + trained steps
        frame_shape: Tuple[int, int],
        lstm_size: int,
        lanes: int = 1,
        stride: Optional[int] = None,  # steps between sequence starts
        priority_exponent: float = 0.9,
        priority_eps: float = 1e-6,
        seed: int = 0,
    ):
        if stride is not None and not (0 < stride <= seq_len):
            raise ValueError("stride must be in (0, seq_len]")
        self.capacity = capacity
        self.L = seq_len
        self.lanes = lanes
        self.stride = stride or max(seq_len // 2, 1)
        self.omega = priority_exponent
        self.eps = priority_eps
        self.rng = np.random.default_rng(seed)

        h, w = frame_shape
        self.frames = np.zeros((capacity, seq_len, h, w), np.uint8)
        self.actions = np.zeros((capacity, seq_len), np.int32)
        self.rewards = np.zeros((capacity, seq_len), np.float32)
        self.dones = np.zeros((capacity, seq_len), bool)
        self.valids = np.zeros((capacity, seq_len), bool)
        self.init_c = np.zeros((capacity, lstm_size), np.float32)
        self.init_h = np.zeros((capacity, lstm_size), np.float32)

        self.tree = SumTree(capacity)
        self.pos = 0
        self.filled = 0
        self.max_priority = 1.0
        # same single-writer discipline as PrioritizedReplay: serialise
        # append/sample/update so a prefetch thread never sees partial state
        self._lock = threading.Lock()
        self._frontier = None  # device sample frontier (attach_frontier)
        # pipeline tracing (obs/pipeline_trace.py): per-slot emit stamps so
        # sample time can attribute sequence age (emit ticks + seconds) —
        # always-on telemetry, no numerics touched
        self._emit_seq = np.zeros(capacity, np.int64)
        self._emit_ts = np.zeros(capacity, np.float64)
        # producing lane per stored sequence (telemetry, like the emit
        # stamps): multi-game runs map lane -> game for per-game learn-share
        # attribution; not persisted in snapshots (restored slots read 0)
        self._slot_lane = np.zeros(capacity, np.int64)
        self.emit_count = 0
        self._tracer = None

        # ---- per-lane builders: step data + the actor LSTM state BEFORE
        # each buffered step (so any window start has its exact state) ------
        self._buf_frames = np.zeros((lanes, seq_len, h, w), np.uint8)
        self._buf_actions = np.zeros((lanes, seq_len), np.int32)
        self._buf_rewards = np.zeros((lanes, seq_len), np.float32)
        self._buf_dones = np.zeros((lanes, seq_len), bool)
        self._buf_c = np.zeros((lanes, seq_len, lstm_size), np.float32)
        self._buf_h = np.zeros((lanes, seq_len, lstm_size), np.float32)
        self._buf_len = np.zeros(lanes, np.int64)
        self._lane_idx = np.arange(lanes)

    # -------------------------------------------------------------- building
    def append_batch(
        self,
        frames: np.ndarray,  # [lanes, H, W] uint8 — frame the action saw
        actions: np.ndarray,
        rewards: np.ndarray,
        terminals: np.ndarray,  # [lanes] bool — TRUE env terminals only
        lstm_c: np.ndarray,  # [lanes, lstm] actor state BEFORE this step
        lstm_h: np.ndarray,
        truncations: Optional[np.ndarray] = None,  # [lanes] bool — time-limit cuts
    ) -> int:
        """Push one lockstep tick; emits completed sequences. Returns the
        number of sequences emitted this tick.

        Both terminals and truncations flush the lane's builder (the episode
        stream breaks there), but only terminals are stored in the sequence's
        `done` channel — the learn step bootstraps through a truncation from
        whatever valid data exists before it, never teaching V=0 at the cut.
        """
        with self._lock:
            return self._append_locked(
                frames, actions, rewards, terminals, lstm_c, lstm_h, truncations
            )

    def _append_locked(
        self, frames, actions, rewards, terminals, lstm_c, lstm_h, truncations
    ):
        if truncations is None:
            truncations = np.zeros(self.lanes, bool)
        # vectorised scatter into each lane's builder row (the per-lane
        # Python loop only runs for lanes that EMIT this tick — rare)
        lane = self._lane_idx
        k = self._buf_len
        self._buf_frames[lane, k] = frames
        self._buf_actions[lane, k] = actions
        self._buf_rewards[lane, k] = rewards
        self._buf_dones[lane, k] = np.asarray(terminals, bool)
        self._buf_c[lane, k] = lstm_c
        self._buf_h[lane, k] = lstm_h
        self._buf_len += 1

        cut = np.asarray(terminals, bool) | np.asarray(truncations, bool)
        emit = cut | (self._buf_len == self.L)
        emitted = 0
        for i in np.flatnonzero(emit):
            emitted += self._emit(int(i), flush=bool(cut[i]))
        return emitted

    def _emit(self, lane: int, flush: bool) -> int:
        """Store the lane's buffered window as one sequence.  On flush
        (terminal) the builder restarts empty; otherwise the last
        L - stride steps carry over so adjacent sequences overlap, seeded
        with the exact stored state from the per-step history."""
        k = int(self._buf_len[lane])
        if k == 0:
            return 0
        slot = self.pos
        for store, buf in (
            (self.frames, self._buf_frames),
            (self.actions, self._buf_actions),
            (self.rewards, self._buf_rewards),
            (self.dones, self._buf_dones),
        ):
            store[slot] = 0
            store[slot, :k] = buf[lane, :k]
        self.valids[slot] = False
        self.valids[slot, :k] = True
        self.init_c[slot] = self._buf_c[lane, 0]
        self.init_h[slot] = self._buf_h[lane, 0]
        self.tree.set(np.asarray([slot]), np.asarray([self.max_priority]))
        if self._frontier is not None:
            self._frontier.stage(
                np.asarray([slot]), np.asarray([self.max_priority])
            )
        self.emit_count += 1
        self._emit_seq[slot] = self.emit_count
        self._emit_ts[slot] = time.time()
        self._slot_lane[slot] = lane
        self.pos = (self.pos + 1) % self.capacity
        self.filled = min(self.filled + 1, self.capacity)

        if flush:
            self._buf_len[lane] = 0
        else:
            tail = self.L - self.stride
            if tail > 0:
                for buf in (
                    self._buf_frames,
                    self._buf_actions,
                    self._buf_rewards,
                    self._buf_dones,
                    self._buf_c,
                    self._buf_h,
                ):
                    buf[lane, :tail] = buf[lane, self.stride :].copy()
            self._buf_len[lane] = tail
        return 1

    def __len__(self) -> int:
        return self.filled

    @property
    def sampleable(self) -> bool:
        return self.tree.total > 0

    def attach_frontier(self, frontier) -> None:
        """Device-sampling wiring (replay/frontier.py): emitted sequences
        stage their slot priority to the HBM mirror."""
        self._frontier = frontier

    def attach_tracer(self, tracer) -> None:
        """Pipeline-tracing wiring (obs/pipeline_trace.py): sample/assemble
        record batch sequence-age lags on the shared registry."""
        self._tracer = tracer

    def lane_of(self, idx: np.ndarray) -> np.ndarray:
        """Producing lane of each stored sequence slot (0 for restored
        slots — the stamps are telemetry, not persisted)."""
        return self._slot_lane[np.asarray(idx, np.int64)]

    def slot_lanes(self) -> np.ndarray:
        """Producing lane of every written slot ([filled])."""
        return self._slot_lane[: self.filled]

    def trace_ids(self, idx: np.ndarray) -> np.ndarray:
        """Emit tick of each slot in ``idx`` (0 = never stamped)."""
        return self._emit_seq[np.asarray(idx, np.int64)]

    def _record_sample_age(self, idx: np.ndarray) -> None:
        if self._tracer is None or idx.size == 0:
            return
        ts = self._emit_ts[idx]
        written = ts > 0
        if not written.any():
            return
        self._tracer.lag("sample_age_ticks", float(
            (self.emit_count - self._emit_seq[idx][written]).mean()))
        self._tracer.lag("sample_age_s",
                         float((time.time() - ts[written]).mean()))

    # -------------------------------------------------------------- sampling
    def sample(self, batch_size: int, beta: float) -> SequenceSample:
        hostsync.check_host_work("replay_sample")
        with self._lock:
            return self._sample_locked(batch_size, beta)

    def assemble_idx(
        self, idx: np.ndarray, weight: np.ndarray,
        prob: Optional[np.ndarray] = None,
    ) -> SequenceSample:
        """Index-driven sequence gather at already-drawn slot ids (the
        device-sampling path: the frontier drew ``idx`` and computed
        ``weight`` in HBM)."""
        idx = np.asarray(idx, np.int64).ravel()
        if idx.size and (idx.min() < 0 or idx.max() >= self.capacity):
            raise IndexError(f"assemble idx out of range [0, {self.capacity})")
        with self._lock:
            self._record_sample_age(idx)
            return SequenceSample(
                idx=idx,
                obs=self.frames[idx][..., None],
                action=self.actions[idx],
                reward=self.rewards[idx],
                done=self.dones[idx],
                valid=self.valids[idx],
                init_c=self.init_c[idx],
                init_h=self.init_h[idx],
                weight=np.asarray(weight, np.float32).ravel(),
                prob=None if prob is None else np.asarray(prob).ravel(),
            )

    def _sample_locked(self, batch_size: int, beta: float) -> SequenceSample:
        idx, prob = self.tree.sample_stratified(batch_size, self.rng)
        self._record_sample_age(idx)
        prob = np.maximum(prob, 1e-12)
        weights = (self.filled * prob) ** (-beta)
        weights = (weights / weights.max()).astype(np.float32)
        return SequenceSample(
            idx=idx,
            obs=self.frames[idx][..., None],
            action=self.actions[idx],
            reward=self.rewards[idx],
            done=self.dones[idx],
            valid=self.valids[idx],
            init_c=self.init_c[idx],
            init_h=self.init_h[idx],
            weight=weights,
            prob=prob,
        )

    def update_priorities(self, idx: np.ndarray, td_mix: np.ndarray) -> None:
        with self._lock:
            pri = (np.asarray(td_mix, np.float64) + self.eps) ** self.omega
            self.max_priority = max(self.max_priority, float(pri.max()))
            self.tree.set(idx, pri)

    # -------------------------------------------------------------- snapshot
    def snapshot(self, path: str) -> None:
        """Persist sequences AND the per-lane builder windows (so a resumed
        run continues mid-episode without losing the partial window)."""
        from rainbow_iqn_apex_tpu.replay import snapshot_io

        with self._lock:
            snapshot_io.atomic_savez(
                path,
                frames=self.frames,
                actions=self.actions,
                rewards=self.rewards,
                dones=self.dones,
                valids=self.valids,
                init_c=self.init_c,
                init_h=self.init_h,
                tree=self.tree.tree,
                pos=self.pos,
                filled=self.filled,
                max_priority=self.max_priority,
                buf_frames=self._buf_frames,
                buf_actions=self._buf_actions,
                buf_rewards=self._buf_rewards,
                buf_dones=self._buf_dones,
                buf_c=self._buf_c,
                buf_h=self._buf_h,
                buf_len=self._buf_len,
            )

    def restore(self, path: str) -> None:
        from rainbow_iqn_apex_tpu.replay import snapshot_io

        z = snapshot_io.load(path)
        if z["frames"].shape != self.frames.shape:
            raise ValueError(
                f"snapshot shape {z['frames'].shape} != buffer {self.frames.shape}"
            )
        with self._lock:
            for name, arr in (
                ("frames", self.frames), ("actions", self.actions),
                ("rewards", self.rewards), ("dones", self.dones),
                ("valids", self.valids), ("init_c", self.init_c),
                ("init_h", self.init_h), ("buf_frames", self._buf_frames),
                ("buf_actions", self._buf_actions),
                ("buf_rewards", self._buf_rewards),
                ("buf_dones", self._buf_dones), ("buf_c", self._buf_c),
                ("buf_h", self._buf_h), ("buf_len", self._buf_len),
            ):
                arr[:] = z[name]
            self.tree.tree[:] = z["tree"]
            self.pos = int(z["pos"])
            self.filled = int(z["filled"])
            self.max_priority = float(z["max_priority"])
        if self._frontier is not None:
            self._frontier.refresh_from_host()
