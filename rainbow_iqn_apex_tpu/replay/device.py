"""Device-resident prioritized replay: the ring, the priorities, and every
sample/update in HBM, so a learner step needs ZERO per-step host transfer.

Why this exists (round-2 measurement, docs/STATUS.md): on the TPU the full
learn step is 0.53 ms but feeding it a host-sampled batch costs 5-8 ms of
host->device transfer — the learner is >90% transfer-bound.  The reference
solves replay with a NETWORK hop (Redis, SURVEY.md §2 row 6); the host-DRAM
shards (replay/buffer.py) replace that hop with a PCIe hop; this module
removes the hop entirely for the capacity that fits in HBM: an Atari-shaped
1M-frame ring is ~7 GB uint8 — comfortable on one modern TPU chip.  This is
the Podracer/"Anakin" arrangement (PAPERS.md): experience, priorities and
the learner state co-resident on device, the whole sample->learn->priority
cycle one XLA graph, and the only host traffic the obligatory fresh frames
(one [L, H, W] uint8 tick, ~7 KB/lane).

Semantics: bit-faithful mirror of the host PrioritizedReplay
(replay/buffer.py) — multi-lane ring with per-lane episode adjacency,
frame-dedup stack reconstruction with cut-zeroing, n-step assembly stopping
at terminals, two-channel terminal/truncation cuts with the unbiased
time-limit rule (a window whose first cut is a truncation is ineligible),
write-cursor dead zone, proportional stratified sampling over p^omega, IS
weights (N P)^-beta max-normalised, and never-resurrect priority
write-back.  tests/test_device_replay.py drives both replays through the
same trace and asserts equality of eligibility, assembly, and weights.

No sum-tree on device: sampling is an O(N) masked cumsum + searchsorted,
which at 1M slots is a few MB of sequential HBM traffic — micro-seconds on
TPU and embarrassingly fusable, where the host's pointer-chasing tree is
exactly the part that needed a C++ core.  (f32 cumsum precision over 1M
slots is ~1e-2 relative worst-case; sampling noise of that size is
irrelevant to PER and the same order as the host tree's f32 leaves.)
"""

from __future__ import annotations

from typing import Optional, Tuple

import chex
import jax
import jax.numpy as jnp
from flax import struct

from rainbow_iqn_apex_tpu.ops.learn import Batch


@struct.dataclass
class DeviceReplayState:
    """The whole replay as one device pytree (donate through append/learn)."""

    frames: jnp.ndarray  # [L, S, H, W] uint8
    actions: jnp.ndarray  # [L, S] int32
    rewards: jnp.ndarray  # [L, S] f32
    terminals: jnp.ndarray  # [L, S] bool — true env terminals (stop bootstrap)
    cuts: jnp.ndarray  # [L, S] bool — terminal OR truncation (stream breaks)
    priority: jnp.ndarray  # [L*S] f32 tree-space p^omega; 0 = ineligible
    pos: jnp.ndarray  # [] int32 lane-local write cursor
    filled: jnp.ndarray  # [] int32 lane-local written count (<= S)
    max_priority: jnp.ndarray  # [] f32 tree-space default for fresh items


class DeviceReplay:
    """Static configuration + pure jittable ops over DeviceReplayState.

    All methods are pure functions (state in, state out) safe to close over
    in jit/scan; the class holds only static shape/hyper parameters.
    """

    def __init__(
        self,
        lanes: int,
        seg: int,  # slots per lane (capacity = lanes * seg)
        frame_shape: Tuple[int, int],
        history: int = 4,
        n_step: int = 3,
        gamma: float = 0.99,
        priority_exponent: float = 0.5,
        priority_eps: float = 1e-6,
    ):
        if seg <= history + n_step:
            raise ValueError("per-lane segment too small for history + n_step")
        self.lanes = lanes
        self.seg = seg
        self.frame_shape = frame_shape
        self.history = history
        self.n_step = n_step
        self.gamma = gamma
        self.omega = priority_exponent
        self.eps = priority_eps
        self._lane_base = jnp.arange(lanes, dtype=jnp.int32) * seg
        self._gammas = gamma ** jnp.arange(n_step + 1, dtype=jnp.float32)

    # ------------------------------------------------------------------ init
    def init_state(self) -> DeviceReplayState:
        h, w = self.frame_shape
        L, S = self.lanes, self.seg
        return DeviceReplayState(
            frames=jnp.zeros((L, S, h, w), jnp.uint8),
            actions=jnp.zeros((L, S), jnp.int32),
            rewards=jnp.zeros((L, S), jnp.float32),
            terminals=jnp.zeros((L, S), bool),
            cuts=jnp.zeros((L, S), bool),
            priority=jnp.zeros((L * S,), jnp.float32),
            pos=jnp.zeros((), jnp.int32),
            filled=jnp.zeros((), jnp.int32),
            max_priority=jnp.ones((), jnp.float32),
        )

    # ---------------------------------------------------------------- append
    def append(
        self,
        state: DeviceReplayState,
        frames: jnp.ndarray,  # [L, H, W] uint8
        actions: jnp.ndarray,  # [L] int32
        rewards: jnp.ndarray,  # [L] f32
        terminals: jnp.ndarray,  # [L] bool
        truncations: jnp.ndarray,  # [L] bool
        priorities: Optional[jnp.ndarray] = None,  # [L] raw |TD| or None
    ) -> DeviceReplayState:
        """One lockstep tick of all lanes (mirror of _append_locked,
        replay/buffer.py): ring writes + the three disjoint priority groups
        (fresh slot -> 0, cursor dead zone -> 0, the slot n_step back ->
        eligible with its actor priority / max_priority, unless its window's
        first cut is a truncation)."""
        L, S, h, n = self.lanes, self.seg, self.history, self.n_step
        pos, filled = state.pos, state.filled
        cuts_now = terminals | truncations

        frames_a = state.frames.at[:, pos].set(frames)
        actions_a = state.actions.at[:, pos].set(actions.astype(jnp.int32))
        rewards_a = state.rewards.at[:, pos].set(rewards.astype(jnp.float32))
        terms_a = state.terminals.at[:, pos].set(terminals)
        cuts_a = state.cuts.at[:, pos].set(cuts_now)

        new_pos = (pos + 1) % S
        fresh_slots = self._lane_base + pos  # [L]
        dead_cols = (new_pos + jnp.arange(h, dtype=jnp.int32)) % S  # [h]
        dead_slots = (self._lane_base[:, None] + dead_cols[None, :]).ravel()

        ready_col = (pos - n) % S
        ready_slots = self._lane_base + ready_col
        if priorities is None:
            pri = jnp.full((L,), state.max_priority)
            new_maxp = state.max_priority
        else:
            pri = (priorities.astype(jnp.float32) + self.eps) ** self.omega
            new_maxp = jnp.where(
                filled >= n,
                jnp.maximum(state.max_priority, pri.max()),
                state.max_priority,
            )
        # unbiased time-limit rule: window [ready, ready+n) whose FIRST cut
        # is a truncation can never form a correct bootstrap -> ineligible
        w_cols = (ready_col + jnp.arange(n, dtype=jnp.int32)) % S  # [n]
        cuts_w = cuts_a[:, w_cols]  # [L, n]
        terms_w = terms_a[:, w_cols]
        first_cut = jnp.argmax(cuts_w, axis=1)  # [L]
        has_cut = cuts_w.any(axis=1)
        first_is_trunc = ~jnp.take_along_axis(
            terms_w, first_cut[:, None], axis=1
        )[:, 0]
        pri = jnp.where(has_cut & first_is_trunc, 0.0, pri)
        # before n_step appends exist, the ready slot has no complete future
        pri = jnp.where(filled >= n, pri, state.priority[ready_slots])

        priority_a = state.priority.at[fresh_slots].set(0.0)
        priority_a = priority_a.at[dead_slots].set(0.0)
        priority_a = priority_a.at[ready_slots].set(pri)

        return DeviceReplayState(
            frames=frames_a,
            actions=actions_a,
            rewards=rewards_a,
            terminals=terms_a,
            cuts=cuts_a,
            priority=priority_a,
            pos=new_pos,
            filled=jnp.minimum(filled + 1, S),
            max_priority=new_maxp,
        )

    # ---------------------------------------------------------------- sample
    def _gather_stacks(
        self, state: DeviceReplayState, lane: jnp.ndarray, off: jnp.ndarray
    ) -> jnp.ndarray:
        """[B, H, W, history] stacks ending at lane-local `off`, zeroing
        frames at/before an episode cut inside the lookback window and
        frames older than a young buffer has written (mirror of
        _gather_stacks, replay/buffer.py)."""
        h, S = self.history, self.seg
        steps = jnp.arange(-(h - 1), 1, dtype=jnp.int32)  # [-h+1 .. 0]
        offs = (off[:, None] + steps[None, :]) % S  # [B, h]
        stacks = state.frames[lane[:, None], offs]  # [B, h, H, W]

        cut_w = state.cuts[lane[:, None], offs[:, :-1]]  # [B, h-1]
        # dead_tail[j] = any cut at/after window position j
        dead_tail = (
            jnp.cumsum(cut_w[:, ::-1].astype(jnp.int32), axis=1)[:, ::-1] > 0
        )
        valid = jnp.concatenate(
            [~dead_tail, jnp.ones((off.shape[0], 1), bool)], axis=1
        )
        age_ok = (off[:, None] + steps[None, :]) >= 0
        valid &= jnp.where(state.filled >= S, True, age_ok)
        stacks = stacks * valid[:, :, None, None].astype(jnp.uint8)
        return jnp.moveaxis(stacks, 1, -1)  # [B, H, W, h]

    def draw(
        self, state: DeviceReplayState, key: chex.PRNGKey, batch_size: int
    ) -> jnp.ndarray:
        """Stratified proportional draw over p^omega (the tree-free
        equivalent of SumTree.sample_stratified): one uniform per stratum,
        inverse-CDF via searchsorted."""
        p = state.priority
        total = p.sum()
        cdf = jnp.cumsum(p)
        u = (jnp.arange(batch_size) + jax.random.uniform(key, (batch_size,)))
        u = u / batch_size * total
        return jnp.clip(
            jnp.searchsorted(cdf, u, side="right"), 0, p.shape[0] - 1
        ).astype(jnp.int32)

    def assemble(
        self,
        state: DeviceReplayState,
        idx: jnp.ndarray,
        beta: jnp.ndarray,
        *,
        with_weight: bool = True,
    ) -> Tuple[Batch, jnp.ndarray]:
        """n-step assembly + stack gathers + IS weights at given global slot
        ids.  Returns (Batch, prob [B]).

        ``with_weight=False`` skips the locally max-normalised IS weight
        (batch.weight comes back as ones) for callers that derive a globally
        consistent weight from ``prob`` instead — the sharded learner's
        pmax-normalised mixture formula (build_device_learn_sharded)."""
        B, S, n = idx.shape[0], self.seg, self.n_step
        p = state.priority
        total = p.sum()
        prob = jnp.maximum(p[idx] / jnp.maximum(total, 1e-12), 1e-12)

        lane = idx // S
        off = idx % S

        steps = jnp.arange(n, dtype=jnp.int32)
        f_offs = (off[:, None] + steps[None, :]) % S  # [B, n]
        r = state.rewards[lane[:, None], f_offs]
        d = state.terminals[lane[:, None], f_offs]
        alive = jnp.cumprod(1.0 - d[:, :-1].astype(jnp.float32), axis=1)
        alive = jnp.concatenate([jnp.ones((B, 1), jnp.float32), alive], axis=1)
        reward = (r * alive * self._gammas[None, :n]).sum(axis=1)
        done_within = d.any(axis=1)
        discount = jnp.where(done_within, 0.0, self._gammas[n])

        obs = self._gather_stacks(state, lane, off)
        next_obs = self._gather_stacks(state, lane, (off + n) % S)

        if with_weight:
            n_stored = (state.filled * self.lanes).astype(jnp.float32)
            w = (n_stored * prob) ** (-beta)
            weight = w / w.max()
        else:
            weight = jnp.ones_like(prob)

        batch = Batch(
            obs=obs,
            action=state.actions[lane, off],
            reward=reward,
            next_obs=next_obs,
            discount=discount,
            weight=weight,
        )
        return batch, prob

    def sample(
        self,
        state: DeviceReplayState,
        key: chex.PRNGKey,
        batch_size: int,
        beta: jnp.ndarray,
    ) -> Tuple[jnp.ndarray, Batch, jnp.ndarray]:
        """Stratified proportional sample + n-step assembly + IS weights.
        Returns (idx [B] int32 global slots, Batch, prob [B])."""
        idx = self.draw(state, key, batch_size)
        batch, prob = self.assemble(state, idx, beta)
        return idx, batch, prob

    def sample_grouped(
        self,
        state: DeviceReplayState,
        key: chex.PRNGKey,
        batch_size: int,
        groups: int,
        beta: jnp.ndarray,
    ) -> Tuple[jnp.ndarray, Batch, jnp.ndarray]:
        """``groups`` independent stratified draws of ``batch_size``,
        concatenated into ONE [G*B] learn batch — the TPU batch-scaling knob
        (SURVEY §7): a 4x bigger GEMM for the MXU without changing the
        reference's PER semantics, because each group keeps the batch-32
        stratum width (total/B per stratum) and its OWN max-normalised IS
        weights, exactly as G sequential reference learn steps would.  What
        DOES differ from G sequential steps: priorities aren't updated
        between draws (groups sample the same distribution) and the
        optimiser takes one step on the G*B mean gradient instead of G
        steps — the standard large-batch trade, chosen explicitly via
        cfg.sample_groups.

        Returns (idx [G, B], Batch over [G*B], prob [G*B])."""
        keys = jax.random.split(key, groups)
        idx = jax.vmap(lambda k: self.draw(state, k, batch_size))(keys)
        batch, prob = self.assemble(
            state, idx.reshape(-1), beta, with_weight=False
        )
        n_stored = (state.filled * self.lanes).astype(jnp.float32)
        w = (n_stored * prob) ** (-beta)
        w = w.reshape(groups, batch_size)
        w = w / w.max(axis=1, keepdims=True)  # per-group, as sequential steps
        return idx, batch.replace(weight=w.reshape(-1)), prob

    # ------------------------------------------------------------- priorities
    def update_priorities_grouped(
        self, state: DeviceReplayState, idx: jnp.ndarray, td_abs: jnp.ndarray
    ) -> DeviceReplayState:
        """Write-back for sample_grouped's [G, B] indices with G-sequential
        semantics: on a slot drawn by several groups, the LAST group's
        priority stands (scatter order across duplicate ids inside one
        .at[].set is unspecified, so the groups are applied as G small
        ordered scatters — G is static and tiny)."""
        G = idx.shape[0]
        td = td_abs.reshape(G, -1)
        for g in range(G):
            state = self.update_priorities(state, idx[g], td[g])
        return state

    def update_priorities(
        self, state: DeviceReplayState, idx: jnp.ndarray, td_abs: jnp.ndarray
    ) -> DeviceReplayState:
        """Learner write-back, never resurrecting cursor-invalidated slots
        (mirror of update_priorities, replay/buffer.py)."""
        pri = (td_abs.astype(jnp.float32) + self.eps) ** self.omega
        new_maxp = jnp.maximum(state.max_priority, pri.max())
        current = state.priority[idx]
        pri = jnp.where(current > 0, pri, 0.0)
        return state.replace(
            priority=state.priority.at[idx].set(pri), max_priority=new_maxp
        )


def _shard_map():
    """jax.shard_map (stable since jax 0.6; replication checks on — every
    out_spec below is either shard-varying or provably replicated)."""
    try:
        return jax.shard_map
    except AttributeError:  # pragma: no cover — older jax
        from jax.experimental.shard_map import shard_map

        return shard_map


def build_device_learn_sharded(cfg, num_actions: int, local_replay: DeviceReplay, mesh, axis: str = "dp"):
    """Multi-chip Anakin: the HBM replay lane-sharded over the mesh's dp axis,
    the learn step dp-sharded as usual — zero host traffic per step on every
    chip.

    Scheme (the in-graph twin of the multi-host sharded replay,
    parallel/multihost.py): each device draws a FIXED batch/n quota from its
    OWN lane shard — static shapes, no cross-device gathers of frames — which
    makes global sampling a uniform mixture over shards; IS weights are
    re-derived from that mixture probability q(i) = prob_local(i)/n and
    max-normalised across all shards with one tiny pmax collective
    (`global_is_nq` math).  The gradient all-reduce stays GSPMD-inserted
    from the batch sharding, exactly as in the host-fed apex learner.

    `local_replay` must be configured with the PER-DEVICE lane count
    (total_lanes // n_devices); the replay state passed to the returned
    function is the GLOBAL state, lane-sharded over `axis` (scalars
    replicated) — see `device_replay_specs`.
    """
    from rainbow_iqn_apex_tpu.ops.learn import build_learn_step

    P = jax.sharding.PartitionSpec
    n_dev = mesh.shape[axis]
    if cfg.batch_size % n_dev:
        raise ValueError(f"batch {cfg.batch_size} not divisible by {n_dev} devices")
    b_loc = cfg.batch_size // n_dev
    groups = getattr(cfg, "sample_groups", 1)
    learn_step = build_learn_step(cfg, num_actions)
    state_spec = device_replay_specs(axis)
    batch_spec = Batch(
        obs=P(axis), action=P(axis), reward=P(axis),
        next_obs=P(axis), discount=P(axis), weight=P(axis),
    )
    smap = _shard_map()

    def _draw_assemble(ds_loc, key, beta):
        """Per-shard fixed-quota draw; with cfg.sample_groups > 1 each shard
        draws G stratified groups of b_loc (flattened [G*b_loc], group g at
        rows [g*b_loc, (g+1)*b_loc)) and IS weights are pmax-normalised PER
        GROUP across shards — the sharded twin of sample_grouped, keeping
        each group's weights exactly what a sequential reference step would
        use."""
        k = jax.random.fold_in(key, jax.lax.axis_index(axis))
        if groups > 1:
            keys = jax.random.split(k, groups)
            idx = jax.vmap(
                lambda kk: local_replay.draw(ds_loc, kk, b_loc)
            )(keys).reshape(-1)
        else:
            idx = local_replay.draw(ds_loc, k, b_loc)
        batch, prob = local_replay.assemble(ds_loc, idx, beta, with_weight=False)
        # globally consistent IS weights over the shard mixture
        n_global = (ds_loc.filled * local_replay.lanes * n_dev).astype(jnp.float32)
        nq = jnp.maximum(n_global * prob / n_dev, 1e-12)
        w = nq ** (-beta)
        wg = w.reshape(groups, b_loc)
        wmax = jax.lax.pmax(wg.max(axis=1), axis)  # [G] per-group global max
        w = (wg / wmax[:, None]).reshape(-1)
        return idx, batch.replace(weight=w)

    def _write_back(ds_loc, idx, td_abs):
        if groups > 1:
            ds_loc = local_replay.update_priorities_grouped(
                ds_loc, idx.reshape(groups, b_loc), td_abs
            )
        else:
            ds_loc = local_replay.update_priorities(ds_loc, idx, td_abs)
        # keep the replicated max_priority scalar shard-consistent
        return ds_loc.replace(
            max_priority=jax.lax.pmax(ds_loc.max_priority, axis)
        )

    draw_assemble = smap(
        _draw_assemble, mesh=mesh,
        in_specs=(state_spec, P(), P()),
        out_specs=(P(axis), batch_spec),
    )
    write_back = smap(
        _write_back, mesh=mesh,
        in_specs=(state_spec, P(axis), P(axis)),
        out_specs=state_spec,
    )

    def _check_geometry(replay_state):
        got = replay_state.frames.shape[0]
        want = local_replay.lanes * n_dev
        if got != want:
            raise ValueError(
                f"sharded device replay geometry mismatch: global state has "
                f"{got} lanes but local_replay.lanes ({local_replay.lanes}) x "
                f"{n_dev} devices = {want}"
            )
        got_seg = replay_state.frames.shape[1]
        if got_seg != local_replay.seg:
            # a seg mismatch would silently mis-decode lane = idx // seg
            # (gather clamps instead of erroring), so refuse loudly
            raise ValueError(
                f"sharded device replay geometry mismatch: global state has "
                f"seg={got_seg} but local_replay.seg={local_replay.seg}"
            )

    def fused(train_state, replay_state, key, beta):
        _check_geometry(replay_state)
        k_sample, k_learn = jax.random.split(key)
        idx, batch = draw_assemble(replay_state, k_sample, beta)
        train_state, info = learn_step(train_state, batch, k_learn)
        replay_state = write_back(replay_state, idx, info["priorities"])
        return train_state, replay_state, info

    # exposed for tests: the in-graph per-shard draw with globally corrected
    # IS weights, without the learn half
    fused.draw_assemble = lambda replay_state, key, beta: (
        _check_geometry(replay_state) or draw_assemble(replay_state, key, beta)
    )
    return fused


def device_replay_specs(axis: str = "dp"):
    """PartitionSpecs for a lane-sharded DeviceReplayState: every per-lane
    array sharded on its lane dimension, cursor scalars replicated."""
    P = jax.sharding.PartitionSpec
    return DeviceReplayState(
        frames=P(axis), actions=P(axis), rewards=P(axis),
        terminals=P(axis), cuts=P(axis), priority=P(axis),
        pos=P(), filled=P(), max_priority=P(),
    )


def device_replay_shardings(mesh, axis: str = "dp"):
    """NamedShardings for placing a global DeviceReplayState on `mesh`:
    `jax.device_put(state, device_replay_shardings(mesh))`.  Wraps
    device_replay_specs in the tree-map callers would otherwise have to
    repeat (PartitionSpec is itself a pytree, hence the is_leaf guard)."""
    P = jax.sharding.PartitionSpec
    return jax.tree.map(
        lambda s: jax.sharding.NamedSharding(mesh, s),
        device_replay_specs(axis),
        is_leaf=lambda x: isinstance(x, P),
    )


def build_device_learn(cfg, num_actions: int, replay: DeviceReplay):
    """The Anakin learner tick: sample -> learn -> priority write-back as ONE
    jittable pure function (train_state, replay_state, key, beta) ->
    (train_state, replay_state, info).  Zero host traffic per step; jit with
    donate_argnums=(0, 1) so both states update in place in HBM."""
    from rainbow_iqn_apex_tpu.ops.learn import build_learn_step

    learn_step = build_learn_step(cfg, num_actions)
    groups = getattr(cfg, "sample_groups", 1)

    def fused(train_state, replay_state, key, beta):
        k_sample, k_learn = jax.random.split(key)
        if groups > 1:
            idx, batch, _prob = replay.sample_grouped(
                replay_state, k_sample, cfg.batch_size, groups, beta
            )
            train_state, info = learn_step(train_state, batch, k_learn)
            replay_state = replay.update_priorities_grouped(
                replay_state, idx, info["priorities"]
            )
        else:
            idx, batch, _prob = replay.sample(
                replay_state, k_sample, cfg.batch_size, beta
            )
            train_state, info = learn_step(train_state, batch, k_learn)
            replay_state = replay.update_priorities(
                replay_state, idx, info["priorities"]
            )
        return train_state, replay_state, info

    return fused
