"""Device-resident sample frontier: the Ape-X host replay's priority vector
mirrored into HBM, with one fused XLA draw kernel over it.

Why this exists (ISSUE 6; ROADMAP "in-network experience sampling",
arXiv:2110.13506): PR 5 made the learner's *write-back* side issue zero
blocking transfers per step, but the *sample* side still walked host
sum-trees and assembled batches in NumPy on every step — the flat
0.17–0.36 learn_steps/s host_feed bench rows were sample-side-bound, and
the PR 5 prefetch starvation gauges exist precisely to prove it.  This
module moves the DRAW off the host path:

- ``DeviceSampleFrontier`` mirrors every shard's tree-space priority leaves
  into one device vector ``[num_shards * shard_capacity]`` and draws
  stratified proportional index blocks with the same masked-cumsum +
  searchsorted primitive ``replay/device.py`` already proved for Anakin —
  global indices, sample probabilities and max-normalised IS weights all
  computed on device, ``G`` index-batches per dispatch so the per-batch
  dispatch overhead amortises away.
- Learner priority write-back retires **directly into the mirror** as a
  jitted scatter of the ring's still-on-device ``|TD|`` array
  (``utils/writeback.py`` with ``materialize_priorities=False``) — the
  host sum-tree becomes a *cold-path* source of truth (snapshot/restore,
  readmission re-seed), reconciled from the mirror at ring-drain
  boundaries (``reconcile``).
- Host appends keep writing the host tree as before; each append's three
  disjoint leaf updates (fresh slot, cursor dead zone, ready slot) are
  *staged* as (slot, value) deltas and flushed to the mirror as one
  batched scatter — an async host→device copy of a few dozen floats per
  tick, never a sync.

Sampling DISTRIBUTION parity with the host path: the host draws a
multinomial shard split then stratifies per shard; the frontier stratifies
once over the global vector.  Both sample slot *i* with probability
``p_i / sum(p)`` (tests/test_device_sampling.py chi-squares both against
the exact distribution), and the IS weights use the identical
``(N * P(i))^-beta / max`` formula at fp32 (the same precision trade
replay/device.py documents for the Anakin cumsum).

Fencing (PR 2/4 invariants): ``on_drop`` zeroes the dead shard's mirror
slice, so draws exclude it and the never-resurrect rule (a write-back
lands only where the mirror is already > 0) drops any in-flight lagged
write-back to it on the floor; ``on_readmit`` refreshes the slice from the
host tree under the NEW epoch, so a zombie incarnation's staleness can
never leak through the mirror.  Draw blocks carry an epoch/dead-set stamp;
the sample-ahead pusher (utils/prefetch.py) counts rows served across an
epoch flip as ``sample_ahead_stale_indices_total``.
"""

from __future__ import annotations

import functools
import threading
import time
from typing import Any, Callable, List, Optional, Sequence, Tuple

import numpy as np

from rainbow_iqn_apex_tpu.utils import hostsync


class DrawBlock:
    """One dispatched draw: ``G`` stratified index-batches still on device,
    plus the epoch/dead-set stamp the mirror had when it was drawn."""

    __slots__ = ("idx", "weight", "prob", "stamp", "group_size", "groups")

    def __init__(self, idx, weight, prob, stamp, group_size: int, groups: int):
        self.idx = idx  # [G, B] int32 global slot ids (device)
        self.weight = weight  # [G, B] f32 per-batch max-normalised IS (device)
        self.prob = prob  # [G, B] f32 global sample probability (device)
        self.stamp = stamp  # (epochs tuple, dead frozenset) at draw time
        self.group_size = group_size
        self.groups = groups


class DeviceSampleFrontier:
    """HBM priority mirror + fused stratified draw + in-mirror write-back.

    Built over a list of host ``SumTree``s (one per replay shard, all of
    capacity ``shard_capacity``); ``from_sharded`` / ``from_sequence`` wire
    the two replay flavours.  All mirror mutation (write-back scatters,
    staged-append flushes, drop/readmit slice edits) is serialized by one
    lock — dispatches are async, so the critical sections are microseconds
    and the learner/pusher threads never wait on device completion here.
    """

    def __init__(
        self,
        trees: Sequence,  # SumTree per shard (host truth, cold path)
        shard_capacity: int,
        eps: float,
        omega: float,
        registry=None,
        role: str = "frontier",
        seed: int = 0,
        draw_block: int = 8,
        reseed_max_priority: Optional[Callable[[int, float], None]] = None,
    ):
        import jax
        import jax.numpy as jnp

        self._jax, self._jnp = jax, jnp
        self.trees = list(trees)
        self.cap = int(shard_capacity)
        self.size = len(self.trees) * self.cap
        if self.size >= np.iinfo(np.int32).max:
            raise ValueError("mirror too large for int32 slot ids")
        self.eps = float(eps)
        self.omega = float(omega)
        self.draw_block = max(int(draw_block), 1)
        self._reseed = reseed_max_priority
        self._lock = threading.Lock()
        self._pending: List[Tuple[np.ndarray, np.ndarray]] = []
        self._pending_rows = 0
        self._epochs = [0] * len(self.trees)
        self._dead: set = set()
        self._all_local = np.arange(self.cap, dtype=np.int64)
        self.reconciles = 0
        self._g_reconcile = None
        if registry is not None:
            self._g_reconcile = registry.gauge("mirror_reconcile_s", role)

        N = self.size

        def _draw(mirror, key, beta, n_items, B, G):
            key, sub = jax.random.split(key)
            total = mirror.sum()
            cdf = jnp.cumsum(mirror)
            u = jax.random.uniform(sub, (G, B))
            u = (jnp.arange(B, dtype=jnp.float32)[None, :] + u) / B * total
            idx = jnp.clip(
                jnp.searchsorted(cdf, u.reshape(-1), side="right"), 0, N - 1
            ).astype(jnp.int32).reshape(G, B)
            prob = jnp.maximum(
                mirror[idx] / jnp.maximum(total, 1e-12), 1e-12
            )
            w = (jnp.maximum(n_items, 1.0) * prob) ** (-beta)
            # per-batch max-normalisation: each [B] batch is one learner
            # step, exactly the host formula
            w = (w / w.max(axis=1, keepdims=True)).astype(jnp.float32)
            return key, idx, w, prob

        self._draw_fn = jax.jit(_draw, static_argnames=("B", "G"))

        def _writeback(mirror, idx, td_abs):
            pri = (jnp.abs(td_abs).astype(jnp.float32) + self.eps) ** self.omega
            cur = mirror[idx]
            # never-resurrect: cursor-invalidated AND dead-shard slots stay 0
            # — this is the epoch fence for lagged in-flight write-backs
            pri = jnp.where(cur > 0, pri, 0.0)
            return mirror.at[idx].set(pri)

        self._writeback_fn = jax.jit(_writeback)
        self._scatter_fn = jax.jit(lambda m, i, v: m.at[i].set(v))
        self._slice_fn = jax.jit(
            lambda m, start, vals: jax.lax.dynamic_update_slice(m, vals, (start,))
        )
        self._key = jax.random.PRNGKey(seed)
        self.mirror = jnp.asarray(self._host_leaves())

    # ------------------------------------------------------------ constructors
    @classmethod
    def from_sharded(cls, memory, registry=None, seed: int = 0,
                     draw_block: int = 8) -> "DeviceSampleFrontier":
        """Frontier over a ``parallel.sharded_replay.ShardedReplay``: one
        mirror slice per shard, attached so appends stage deltas and
        drop/readmit fence the mirror (``memory.attach_frontier``)."""
        s0 = memory.shards[0]

        def reseed(k: int, _leaf_max: float) -> None:
            # fresh-item default priority: max over WRITTEN leaves only (the
            # clamped max_leaf — never-written residue must not inflate it)
            shard = memory.shards[k]
            shard.max_priority = max(
                shard.max_priority,
                shard.tree.max_leaf(shard.filled, shard.lanes),
            )

        frontier = cls(
            [s.tree for s in memory.shards],
            memory.shard_capacity,
            eps=s0.eps,
            omega=s0.omega,
            registry=registry,
            seed=seed,
            draw_block=draw_block,
            reseed_max_priority=reseed,
        )
        for k in memory.dead_shards:  # mirror starts fenced like the host
            frontier.on_drop(k)
        memory.attach_frontier(frontier)
        return frontier

    @classmethod
    def from_sequence(cls, memory, registry=None, seed: int = 0,
                      draw_block: int = 8) -> "DeviceSampleFrontier":
        """Frontier over a single ``replay.sequence.SequenceReplay`` (the
        R2D2 path): one tree, no shard epochs."""

        def reseed(_k: int, _leaf_max: float) -> None:
            memory.max_priority = max(
                memory.max_priority, memory.tree.max_leaf(memory.filled)
            )

        frontier = cls(
            [memory.tree],
            memory.capacity,
            eps=memory.eps,
            omega=memory.omega,
            registry=registry,
            seed=seed,
            draw_block=draw_block,
            reseed_max_priority=reseed,
        )
        memory.attach_frontier(frontier)
        return frontier

    # ---------------------------------------------------------------- helpers
    def _host_leaves(self) -> np.ndarray:
        """Current host-tree leaves as one f32 vector (dead shards zeroed —
        the host tree keeps their mass for readmission, the mirror must
        not sample it)."""
        out = np.empty(self.size, np.float32)
        for k, tree in enumerate(self.trees):
            sl = out[k * self.cap:(k + 1) * self.cap]
            if k in self._dead:
                sl[:] = 0.0
            else:
                sl[:] = tree.tree[tree.span:tree.span + self.cap]
        return out

    @property
    def stamp(self) -> Tuple[tuple, frozenset]:
        return (tuple(self._epochs), frozenset(self._dead))

    def stale_rows(self, idx: np.ndarray, stamp) -> int:
        """How many of ``idx`` point into shards whose epoch flipped (drop
        or readmit) since ``stamp`` was taken — the rows a sample-ahead
        batch served past a fence event."""
        epochs, dead = stamp
        changed = [
            k for k in range(len(self.trees))
            if self._epochs[k] != epochs[k] or (k in self._dead) != (k in dead)
        ]
        if not changed:
            return 0
        shard_of = np.asarray(idx).ravel() // self.cap
        # materializing drawn indices at gather time is the design (PR 6):
        # host-sync-ok: runs on the pusher worker thread, not the learner
        return int(np.isin(shard_of, changed).sum())

    # ------------------------------------------------------------------ draw
    def draw(self, batch_size: int, beta: float, n_items: int,
             groups: Optional[int] = None) -> DrawBlock:
        """Dispatch one fused draw of ``groups`` stratified index-batches
        (async — nothing blocks here).  Each [B] row is one learner batch:
        stratified over the global mass exactly like the host's
        multinomial-split + per-shard strata, with its own max-normalised
        IS weights."""
        G = self.draw_block if groups is None else max(int(groups), 1)
        self.flush_staged()
        with self._lock:
            self._key, idx, w, prob = self._draw_fn(
                self.mirror, self._key, float(beta), float(max(n_items, 1)),
                B=int(batch_size), G=G,
            )
            stamp = self.stamp
        return DrawBlock(idx, w, prob, stamp, int(batch_size), G)

    # ------------------------------------------------------------- write-back
    def update(self, idx, td_abs) -> None:
        """Learner priority write-back straight into the mirror (the
        ``RingCommitter`` update target when device sampling is on).  Both
        arguments may still be device arrays — this is a dispatch, not a
        sync.  Duplicate slots within one batch land in unspecified order
        (the host tree keeps the last; PER is insensitive to which of two
        same-step |TD| rows wins).  Staged append deltas flush FIRST so the
        mirror sees them in program order — otherwise a slot the cursor
        just made eligible would drop this write-back on the
        never-resurrect floor while the host tree kept it."""
        self.flush_staged()
        jnp = self._jnp
        with self._lock:
            self.mirror = self._writeback_fn(
                self.mirror, jnp.asarray(idx), jnp.asarray(td_abs)
            )

    # ------------------------------------------------------- append mirroring
    def stage(self, global_idx: np.ndarray, values: np.ndarray) -> None:
        """Queue host-append leaf deltas (tree-space values at global slot
        ids) for the next flush.  Called from the replay's append path on
        the main thread; flushing happens on the pusher thread before each
        draw (or inline past a size threshold, still just an async
        dispatch)."""
        with self._lock:
            self._pending.append((
                np.asarray(global_idx, np.int64).ravel(),
                np.asarray(values, np.float32).ravel(),
            ))
            self._pending_rows += len(self._pending[-1][0])
            flush_now = self._pending_rows >= 4096
        if flush_now:
            self.flush_staged()

    def flush_staged(self) -> None:
        """Apply every staged append delta as one batched scatter (last
        write per slot wins, matching the host tree's sequential order)."""
        with self._lock:
            if not self._pending:
                return
            pending, self._pending, self._pending_rows = self._pending, [], 0
            idx = np.concatenate([i for i, _ in pending])
            vals = np.concatenate([v for _, v in pending])
            if idx.size > 1:  # keep the LAST write per duplicate slot
                _, last_pos = np.unique(idx[::-1], return_index=True)
                keep = idx.size - 1 - last_pos
                idx, vals = idx[keep], vals[keep]
            # dead shards stay fenced: their staged rows (an append racing
            # the drop) must not repopulate the zeroed slice
            if self._dead:
                alive = ~np.isin(idx // self.cap, sorted(self._dead))
                idx, vals = idx[alive], vals[alive]
            if idx.size:
                self.mirror = self._scatter_fn(
                    self.mirror, idx.astype(np.int32), vals
                )

    # -------------------------------------------------------------- elasticity
    def on_drop(self, k: int) -> None:
        """Shard ``k`` died: zero its mirror slice so draws exclude it and
        lagged write-backs to it can never resurrect (the mirror-side twin
        of ``ShardedReplay.drop_shard``)."""
        jnp = self._jnp
        with self._lock:
            self._dead.add(k)
            self._epochs[k] += 1
            self.mirror = self._slice_fn(
                self.mirror, k * self.cap, jnp.zeros((self.cap,), jnp.float32)
            )

    def on_readmit(self, k: int) -> None:
        """Shard ``k`` rejoined under a new lease epoch: refresh its slice
        from the host tree (the cold-path source of truth the rejoining
        host restored or re-seeded)."""
        jnp = self._jnp
        tree = self.trees[k]
        # host-sync-ok: host sum-tree slice on the cold readmission path
        vals = np.asarray(
            tree.tree[tree.span:tree.span + self.cap], np.float32
        )
        with self._lock:
            self._dead.discard(k)
            self._epochs[k] += 1
            self.mirror = self._slice_fn(
                self.mirror, k * self.cap, jnp.asarray(vals)
            )

    def refresh_from_host(self, dead=None) -> None:
        """Reload the whole mirror from the host trees (snapshot restore —
        the cold path rewrote the truth wholesale), optionally adopting the
        owner's restored dead-shard set.  Bumps every shard's frontier
        epoch so in-flight draw blocks read as stale."""
        with self._lock:
            if dead is not None:
                self._dead = set(dead)
            self._pending, self._pending_rows = [], 0
            self._epochs = [e + 1 for e in self._epochs]
            self.mirror = self._jnp.asarray(self._host_leaves())

    # --------------------------------------------------------------- reconcile
    def reconcile(self) -> float:
        """Drain-boundary sync of the COLD path: materialize the mirror
        (sanctioned — drains are already host-device sync points) and write
        it back into the host sum-trees, so snapshots, readmission
        re-seeds, and a later ``device_sampling=off`` run all see the
        learner's priorities.  Returns (and gauges) the wall seconds."""
        t0 = time.perf_counter()
        self.flush_staged()
        with self._lock:
            mirror = self.mirror
        with hostsync.sanctioned():
            host = np.maximum(np.asarray(mirror), 0.0).astype(np.float64)
        for k, tree in enumerate(self.trees):
            if k in self._dead:
                continue  # host tree keeps the dead shard's cold truth
            sl = host[k * self.cap:(k + 1) * self.cap]
            tree.set(self._all_local, sl)
            if self._reseed is not None and sl.size:
                self._reseed(k, float(sl.max()))
        dt = time.perf_counter() - t0
        self.reconciles += 1
        if self._g_reconcile is not None:
            self._g_reconcile.set(dt)
        return dt

    # -------------------------------------------------------------------- test
    def mirror_np(self) -> np.ndarray:
        """Materialize the mirror on host (tests / cold paths only)."""
        with self._lock:
            mirror = self.mirror
        with hostsync.sanctioned():
            return np.asarray(mirror)


def make_batch_assembler(memory, to_device: Callable[[Any], Any],
                         registry=None, role: str = "prefetch"):
    """The pusher's host half for a ShardedReplay: global idx + device
    weights -> staged device Batch (an index-driven frame gather).

    Gather-time cursor fence: indices were drawn against a mirror snapshot,
    and by gather time the ring cursor may have advanced INTO a drawn
    slot's history/n-step window (the lap-straddle race sample-ahead
    opens; the host path closes it by assembling atomically at sample
    time).  The append path keeps every such slot's host-tree leaf at
    zero, so ``eligible_mask`` identifies the invalidated rows exactly —
    their IS weight is zeroed (a zero-weight row contributes nothing to
    the loss, and the never-resurrect rule already drops its priority
    write-back) and they count into ``sample_ahead_stale_indices_total``.
    """
    c_stale = None
    if registry is not None:
        c_stale = registry.counter("sample_ahead_stale_indices_total", role)

    def assemble(idx: np.ndarray, weight: np.ndarray):
        ok = memory.eligible_mask(idx)
        if not ok.all():
            if c_stale is not None:
                # host-sync-ok: host eligible_mask ndarray, pusher thread
                c_stale.inc(int((~ok).sum()))
            weight = np.where(ok, weight, 0.0).astype(np.float32)
        sample = memory.assemble_global(idx, weight)
        # sample.idx, not idx: assemble_global returns rows slot-sorted, and
        # the ring's priority write-back must stay row-aligned with them
        return sample.idx, to_device(sample)

    return assemble
