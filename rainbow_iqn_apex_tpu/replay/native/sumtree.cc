// Native sum-tree hot path for prioritized replay.
//
// Parity: replaces the per-item Python tree walk of the reference's
// rainbowiqn/memory.py SegmentTree (SURVEY.md §2 row 5) — the component
// SURVEY.md §7 singles out as the justified native rewrite: at the build's
// target throughput the host-side tree is on the critical path long before
// the TPU is.
//
// Design: the tree is a NumPy-owned flat double array (implicit binary heap,
// root at 1, leaves at [span, span+capacity)); C++ only runs the loops.
// Keeping storage on the Python side makes snapshots/checkpoints trivial and
// the binding zero-copy.  All functions are plain C ABI for ctypes.

#include <cstdint>

extern "C" {

// Batched leaf assignment + ancestor fix-up. Sequential per item, so
// duplicate indices naturally resolve to last-write-wins (the reference's
// per-item loop semantics).
void st_set(double* tree, int64_t span, const int64_t* idx, const double* pri,
            int64_t n) {
  for (int64_t k = 0; k < n; ++k) {
    int64_t node = span + idx[k];
    double delta = pri[k] - tree[node];
    if (delta == 0.0) continue;
    for (; node >= 1; node >>= 1) tree[node] += delta;
  }
}

// Batched prefix-sum descent: out[k] = leaf index whose cumulative-priority
// interval contains mass[k]. Clamps to capacity-1 (fp edge-fall guard).
void st_find_prefix(const double* tree, int64_t span, int64_t capacity,
                    const double* mass, int64_t* out, int64_t n) {
  for (int64_t k = 0; k < n; ++k) {
    double m = mass[k];
    int64_t node = 1;
    while (node < span) {
      int64_t left = node << 1;
      double lsum = tree[left];
      if (m < lsum) {
        node = left;
      } else {
        m -= lsum;
        node = left + 1;
      }
    }
    int64_t leaf = node - span;
    out[k] = leaf < capacity ? leaf : capacity - 1;
  }
}

// Fused stratified sample: mass[k] pre-drawn by the caller (keeps RNG in
// NumPy for reproducibility); returns leaves and their raw priorities.
void st_sample(const double* tree, int64_t span, int64_t capacity,
               const double* mass, int64_t* out_idx, double* out_pri,
               int64_t n) {
  st_find_prefix(tree, span, capacity, mass, out_idx, n);
  for (int64_t k = 0; k < n; ++k) out_pri[k] = tree[span + out_idx[k]];
}

}  // extern "C"
