// Fused native hot paths for the prioritized frame replay (v2).
//
// Parity: the reference's replay critical path is redis-server's C event
// loop + the per-sample Python assembly in rainbowiqn/memory.py (SURVEY.md
// §2 row 5, §7 "hard parts": the host replay is on the critical path long
// before the accelerator is).  v1 moved the sum-tree walks native
// (sumtree.cc); v2 fuses the remaining per-tick / per-batch work:
//
//   rb_append_tick  — one call per lockstep actor tick: ring writes for all
//                     lanes, fresh/dead-zone/ready-slot priority updates
//                     (including the truncation-eligibility rule), all tree
//                     ancestor fix-ups.
//   rb_assemble     — one call per sampled batch: n-step reward/discount
//                     scan plus BOTH frame-stack gathers, written directly
//                     in the device layout [B, H, W, hist] (uint8), with
//                     episode-cut zeroing and young-buffer age masking.
//
// Storage stays NumPy-owned (zero-copy ctypes, trivial snapshots); C++ only
// runs the loops.  Semantics mirror replay/buffer.py exactly — the fuzz
// test in tests/test_replay.py drives both implementations on identical
// streams and asserts bit-equal trees and batches.

#include <cmath>
#include <cstdint>
#include <cstring>
#include <vector>

namespace {

// Leaf assignment + ancestor fix-up (same walk as sumtree.cc st_set).
inline void leaf_set(double* tree, int64_t span, int64_t leaf, double pri) {
  int64_t node = span + leaf;
  double delta = pri - tree[node];
  if (delta == 0.0) return;
  for (; node >= 1; node >>= 1) tree[node] += delta;
}

inline int64_t mod(int64_t a, int64_t m) {
  int64_t r = a % m;
  return r < 0 ? r + m : r;
}

}  // namespace

extern "C" {

// One lockstep append tick for all lanes.  Mirrors
// PrioritizedReplay._append_locked; pos/filled advance on the Python side.
// priorities may be null (-> every ready slot gets max_priority as-is);
// when given, raw |TD| values are transformed to (p + eps)^omega and
// *max_priority is raised to the batch max BEFORE eligibility zeroing.
void rb_append_tick(
    uint8_t* frames, int32_t* actions, float* rewards, uint8_t* terminals,
    uint8_t* cuts, double* tree, int64_t span,
    int64_t lanes, int64_t seg, int64_t pos, int64_t filled,
    int64_t history, int64_t n_step, int64_t frame_bytes,
    const uint8_t* new_frames, const int32_t* new_actions,
    const float* new_rewards, const uint8_t* new_terminals,
    const uint8_t* new_truncs,  // may be null (-> cuts = terminals)
    const double* priorities,   // may be null
    double eps, double omega, double* max_priority) {
  const int64_t new_pos = (pos + 1) % seg;

  for (int64_t i = 0; i < lanes; ++i) {
    const int64_t slot = i * seg + pos;
    std::memcpy(frames + slot * frame_bytes, new_frames + i * frame_bytes,
                static_cast<size_t>(frame_bytes));
    actions[slot] = new_actions[i];
    rewards[slot] = new_rewards[i];
    terminals[slot] = new_terminals[i];
    cuts[slot] = new_truncs ? (new_terminals[i] | new_truncs[i])
                            : new_terminals[i];
    // fresh slot: not sampleable until its n-step future exists
    leaf_set(tree, span, slot, 0.0);
    // cursor dead zone: lookback windows crossing the write cursor would
    // mix frames from two ring laps
    for (int64_t j = 0; j < history; ++j) {
      leaf_set(tree, span, i * seg + (new_pos + j) % seg, 0.0);
    }
  }

  if (filled >= n_step) {
    const int64_t ready = mod(pos - n_step, seg);
    double mp = *max_priority;
    if (priorities) {
      for (int64_t i = 0; i < lanes; ++i) {
        const double p = std::pow(priorities[i] + eps, omega);
        if (p > mp) mp = p;
      }
      *max_priority = mp;
    }
    for (int64_t i = 0; i < lanes; ++i) {
      double pri = priorities ? std::pow(priorities[i] + eps, omega) : mp;
      // Unbiased time-limit rule: a window whose FIRST cut is a truncation
      // can't form a correct bootstrap target — never eligible.
      for (int64_t w = 0; w < n_step; ++w) {
        const int64_t ws = i * seg + (ready + w) % seg;
        if (cuts[ws]) {
          if (!terminals[ws]) pri = 0.0;
          break;
        }
      }
      leaf_set(tree, span, i * seg + ready, pri);
    }
  }
}

// Batched n-step assembly + both stack gathers in device layout.
// out_obs / out_next_obs: [B, H*W, history] uint8 (channels-last).
void rb_assemble(
    const uint8_t* frames, const int32_t* actions, const float* rewards,
    const uint8_t* terminals, const uint8_t* cuts,
    int64_t seg, int64_t filled, int64_t history, int64_t n_step,
    int64_t frame_bytes, const float* gammas /* [n_step + 1] */,
    const int64_t* idx, int64_t batch,
    uint8_t* out_obs, uint8_t* out_next_obs,
    int32_t* out_action, float* out_reward, float* out_discount) {
  const int64_t h = history;
  // Invalid window frames read from this zero page instead of branching
  // per byte in the interleave loop (keeps it straight-line for the
  // autovectorizer).
  std::vector<uint8_t> zero(static_cast<size_t>(frame_bytes), 0);

  for (int64_t b = 0; b < batch; ++b) {
    const int64_t lane = idx[b] / seg;
    const int64_t off = idx[b] % seg;
    const int64_t base = lane * seg;

    // --- n-step reward scan (truncate at terminal, bootstrap discount) ----
    float rn = 0.0f;
    float alive = 1.0f;  // no terminal strictly before step k
    int done_within = 0;
    for (int64_t k = 0; k < n_step; ++k) {
      const int64_t slot = base + (off + k) % seg;
      if (k > 0) alive *= 1.0f - static_cast<float>(
                              terminals[base + (off + k - 1) % seg]);
      rn += rewards[slot] * alive * gammas[k];
      done_within |= terminals[slot];
    }
    out_reward[b] = rn;
    out_discount[b] = done_within ? 0.0f : gammas[n_step];
    out_action[b] = actions[base + off];

    // --- both stacks, interleaved channels-last --------------------------
    for (int pass = 0; pass < 2; ++pass) {
      const int64_t end = pass ? (off + n_step) % seg : off;
      uint8_t* out = (pass ? out_next_obs : out_obs) + b * frame_bytes * h;

      // validity per window position j (frame at end - (h-1-j)):
      // a cut at window position j < h-1 kills frames [0..j]; in a young
      // buffer, offsets before slot 0 were never written.
      const uint8_t* src[16];  // history <= 16 in any sane config
      for (int64_t j = 0; j < h; ++j) {
        const int64_t rel = end + j - (h - 1);
        src[j] = (filled >= seg || rel >= 0)
                     ? frames + (base + mod(rel, seg)) * frame_bytes
                     : zero.data();
      }
      for (int64_t j = h - 2; j >= 0; --j) {
        if (cuts[base + mod(end + j - (h - 1), seg)]) {
          // cut AT window position j kills frames [0..j]
          for (int64_t k = j; k >= 0; --k) src[k] = zero.data();
          break;  // earlier cuts only re-kill already-dead frames
        }
      }
      if (h == 4) {  // the Atari shape: branchless 4-way byte interleave
        const uint8_t *s0 = src[0], *s1 = src[1], *s2 = src[2], *s3 = src[3];
        for (int64_t p = 0; p < frame_bytes; ++p) {
          uint8_t* o = out + p * 4;
          o[0] = s0[p]; o[1] = s1[p]; o[2] = s2[p]; o[3] = s3[p];
        }
      } else {
        for (int64_t p = 0; p < frame_bytes; ++p) {
          uint8_t* o = out + p * h;
          for (int64_t j = 0; j < h; ++j) o[j] = src[j][p];
        }
      }
    }
  }
}

}  // extern "C"
