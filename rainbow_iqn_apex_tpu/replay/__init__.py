from rainbow_iqn_apex_tpu.replay.buffer import PrioritizedReplay, SampledBatch
from rainbow_iqn_apex_tpu.replay.frontier import DeviceSampleFrontier
from rainbow_iqn_apex_tpu.replay.native import NativeSumTree, native_available
from rainbow_iqn_apex_tpu.replay.sumtree import SumTree

__all__ = [
    "PrioritizedReplay",
    "SampledBatch",
    "SumTree",
    "NativeSumTree",
    "native_available",
    "DeviceSampleFrontier",
]
