"""Prioritized replay: host structures, the device sample frontier, and
the cross-host replay plane (replay/net/).

Exports resolve lazily (PEP 562, the parallel/ pattern): `frontier` is
jax-facing, and eagerly importing it here would taint every jax-free
consumer of the host-side structures — replay/net's shard servers and
actor spoolers import `replay.buffer` from processes with no device
runtime at all (analysis/imports.py declares the contract)."""

from typing import TYPE_CHECKING

_EXPORTS = {
    "PrioritizedReplay": "rainbow_iqn_apex_tpu.replay.buffer",
    "SampledBatch": "rainbow_iqn_apex_tpu.replay.buffer",
    "SumTree": "rainbow_iqn_apex_tpu.replay.sumtree",
    "NativeSumTree": "rainbow_iqn_apex_tpu.replay.native",
    "native_available": "rainbow_iqn_apex_tpu.replay.native",
    "DeviceSampleFrontier": "rainbow_iqn_apex_tpu.replay.frontier",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name: str):
    module = _EXPORTS.get(name)
    if module is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(module), name)


def __dir__():
    return __all__


if TYPE_CHECKING:  # static analyzers see the eager imports
    from rainbow_iqn_apex_tpu.replay.buffer import (  # noqa: F401
        PrioritizedReplay,
        SampledBatch,
    )
    from rainbow_iqn_apex_tpu.replay.frontier import (  # noqa: F401
        DeviceSampleFrontier,
    )
    from rainbow_iqn_apex_tpu.replay.native import (  # noqa: F401
        NativeSumTree,
        native_available,
    )
    from rainbow_iqn_apex_tpu.replay.sumtree import SumTree  # noqa: F401
