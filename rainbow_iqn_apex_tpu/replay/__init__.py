from rainbow_iqn_apex_tpu.replay.sumtree import SumTree

__all__ = ["SumTree"]
