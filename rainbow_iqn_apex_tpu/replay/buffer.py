"""Prioritized replay memory with n-step assembly and frame-dedup storage.

Parity: reference `rainbowiqn/memory.py` `ReplayMemory` (SURVEY.md §2 row 5):
proportional prioritization over p^omega, stratified batch sampling,
importance-sampling weights (N * P(i))^-beta normalised by the batch max,
n-step transition assembly from a ring buffer, and frame de-duplication —
each 84x84 frame is stored once and stacks are reconstructed at sample time.

TPU-first design notes:
- Everything is dense NumPy on the host; the device only ever sees the
  assembled [B, H, W, C] uint8 batch (SURVEY §7: "host replay, device
  batches").  Sampling cost is dominated by two fancy-indexed gathers.
- Multi-lane layout: a batched vector env steps L environments in lockstep
  (the TPU-native actor shape). Each lane owns a contiguous ring segment of
  the buffer so episode adjacency — which both frame-stack reconstruction
  and n-step assembly rely on — is preserved per lane, with one global
  sum-tree over all slots.  This replaces the reference's one-process-one-
  buffer adjacency assumption without giving up dedup.
- The sum-tree hot path can be served by the C++ core (replay/native.py)
  with identical layout; `SumTree` is the NumPy fallback.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Optional, Tuple

import numpy as np

from rainbow_iqn_apex_tpu.replay.sumtree import SumTree
from rainbow_iqn_apex_tpu.utils import hostsync


@dataclasses.dataclass
class SampledBatch:
    """Host-side sample, ready to ship to the device as one transfer."""

    idx: np.ndarray  # [B] int64 global slot ids (for update_priorities)
    obs: np.ndarray  # [B, H, W, hist] uint8
    action: np.ndarray  # [B] int32
    reward: np.ndarray  # [B] float32 — n-step discounted return
    next_obs: np.ndarray  # [B, H, W, hist] uint8
    discount: np.ndarray  # [B] float32 — gamma^n * (1 - done-within-n)
    weight: np.ndarray  # [B] float32 — IS weights, max-normalised
    prob: np.ndarray = None  # [B] float64 — buffer-local sample probability
    # (kept alongside weight so sharded replay can re-derive globally
    # consistent IS weights; see parallel/sharded_replay.py)
    game: np.ndarray = None  # [B] int32 game ids — multi-game runs only
    # (multitask/replay.py attaches them; None on the single-game path)


class PrioritizedReplay:
    """Proportional PER over a multi-lane ring of de-duplicated frames.

    Per-timestep record (lane-local index t): the newest preprocessed frame
    f_t (the last slice of the state the action was chosen from), the action
    a_t, the resulting reward r_t and terminal flag d_t.
    """

    def __init__(
        self,
        capacity: int,
        frame_shape: Tuple[int, int],
        history: int = 4,
        n_step: int = 3,
        gamma: float = 0.99,
        lanes: int = 1,
        priority_exponent: float = 0.5,
        priority_eps: float = 1e-6,
        seed: int = 0,
        use_native: bool = True,
    ):
        if capacity % lanes != 0:
            raise ValueError(f"capacity {capacity} not divisible by lanes {lanes}")
        self.capacity = capacity
        self.lanes = lanes
        self.seg = capacity // lanes  # slots per lane ring
        if self.seg <= history + n_step:
            raise ValueError("per-lane segment too small for history + n_step")
        self.history = history
        self.n_step = n_step
        self.gamma = gamma
        self.omega = priority_exponent
        self.eps = priority_eps
        self.rng = np.random.default_rng(seed)

        h, w = frame_shape
        self.frames = np.zeros((capacity, h, w), dtype=np.uint8)
        self.actions = np.zeros(capacity, dtype=np.int32)
        self.rewards = np.zeros(capacity, dtype=np.float32)
        self.terminals = np.zeros(capacity, dtype=bool)  # true env terminals
        # cuts = terminal OR truncation: where the episode STREAM breaks
        # (frame stacks and n-step windows must not cross a cut; only true
        # terminals stop value bootstrapping — the two-channel design that
        # removes the time-limit bias, docs/DESIGN.md)
        self.cuts = np.zeros(capacity, dtype=bool)

        self.tree: SumTree
        self._core = None  # v2 fused C++ append/assemble (replay/native)
        if use_native:
            from rainbow_iqn_apex_tpu.replay.native import (
                NativeSumTree,
                ReplayCore,
                native_available,
            )

            if native_available():
                self.tree = NativeSumTree(capacity)
                # rb_assemble's per-window scratch is sized for history<=16
                # (any sane stack depth); deeper stacks use the NumPy path
                if history <= 16:
                    self._core = ReplayCore(self)
            else:
                self.tree = SumTree(capacity)
        else:
            self.tree = SumTree(capacity)

        self.pos = 0  # lane-local write cursor (lockstep across lanes)
        self.filled = 0  # lane-local count of written slots (<= seg)
        self.max_priority = 1.0  # tree-space (already ^omega) value for new items
        # Serialises the multi-statement append/sample/update sequences so a
        # background prefetch thread (utils/prefetch.py) never observes a
        # half-applied tree update or a frame array mid-overwrite. Held only
        # for the ~ms host-side critical sections; device compute overlaps
        # freely. This is the explicit single-writer discipline SURVEY §5
        # calls for in place of Redis's single-threaded command loop.
        self._lock = threading.Lock()

        # discount ladder gamma^0..gamma^n, reused every sample
        self._gammas = self.gamma ** np.arange(self.n_step + 1, dtype=np.float32)
        self._lane_base = np.arange(self.lanes, dtype=np.int64) * self.seg

    # ------------------------------------------------------------------ append
    def append_batch(
        self,
        frames: np.ndarray,  # [L, H, W] uint8
        actions: np.ndarray,  # [L]
        rewards: np.ndarray,  # [L]
        terminals: np.ndarray,  # [L] bool — true env terminals (stop bootstrap)
        priorities: Optional[np.ndarray] = None,  # [L] raw |TD| (Ape-X actors)
        truncations: Optional[np.ndarray] = None,  # [L] bool — time-limit cuts
    ) -> np.ndarray:
        """Append one lockstep step of all lanes. Returns global slot ids."""
        L = frames.shape[0]
        if L != self.lanes:
            raise ValueError(f"expected {self.lanes} lanes, got {L}")
        with self._lock:
            return self._append_locked(
                frames, actions, rewards, terminals, priorities, truncations
            )

    def _append_locked(self, frames, actions, rewards, terminals, priorities, truncations):
        if self._core is not None:
            # v2: ring writes + every tree update in one native call
            self.max_priority = self._core.append_tick(
                frames, actions, rewards, terminals, priorities, truncations
            )
            slots = self._lane_base + self.pos
            self.pos = (self.pos + 1) % self.seg
            self.filled = min(self.filled + 1, self.seg)
            return slots
        slots = self._lane_base + self.pos
        self.frames[slots] = frames
        self.actions[slots] = actions
        self.rewards[slots] = rewards
        self.terminals[slots] = terminals
        self.cuts[slots] = (
            terminals if truncations is None else (terminals | truncations)
        )

        # One fused priority write per step covers three DISJOINT slot groups
        # (disjointness holds because seg > history + n_step):
        #  - the fresh slot: not yet sampleable, its n-step future is missing;
        #  - the slot written n_step appends ago: its future is now complete
        #    -> eligible. When actors supply an initial priority (Ape-X), it
        #    is the priority of THAT completed transition, not of this frame;
        #  - the cursor dead zone [new_pos, new_pos+history-1]: slots whose
        #    lookback window would cross the write cursor and mix frames from
        #    two different ring laps. (While the buffer is young these are
        #    unwritten and already zero — harmless.)
        new_pos = (self.pos + 1) % self.seg
        dead = (new_pos + np.arange(self.history)) % self.seg
        dead_slots = (self._lane_base[:, None] + dead[None, :]).ravel()
        upd_idx = [slots, dead_slots]
        upd_pri = [np.zeros(self.lanes), np.zeros(dead_slots.size)]
        if self.filled >= self.n_step:
            ready = (self.pos - self.n_step) % self.seg
            if priorities is None:
                pri = np.full(self.lanes, self.max_priority)
            else:
                pri = (np.asarray(priorities, np.float64) + self.eps) ** self.omega
                self.max_priority = max(self.max_priority, float(pri.max()))
            # Unbiased time-limit handling: a transition whose n-step window
            # hits a TRUNCATION before any terminal cannot form a correct
            # bootstrap target (the post-cut state belongs to a new episode
            # and the pre-cut final state was never stored) — it is simply
            # never eligible, rather than faking a terminal.
            w_offs = (ready + np.arange(self.n_step)) % self.seg
            w_slots = self._lane_base[:, None] + w_offs[None, :]
            cuts_w = self.cuts[w_slots]  # [L, n]
            term_w = self.terminals[w_slots]
            first_cut = cuts_w.argmax(axis=1)
            has_cut = cuts_w.any(axis=1)
            first_is_trunc = ~term_w[np.arange(self.lanes), first_cut]
            pri = np.where(has_cut & first_is_trunc, 0.0, pri)
            upd_idx.append(self._lane_base + ready)
            upd_pri.append(pri)
        self.tree.set(np.concatenate(upd_idx), np.concatenate(upd_pri))

        self.pos = new_pos
        self.filled = min(self.filled + 1, self.seg)
        return slots

    # ------------------------------------------------------------- live retune
    def set_priority_exponent(self, omega: float) -> None:
        """Mid-run omega adoption (league/ live gene): applies to every
        FUTURE append/write-back; existing tree values keep their old
        exponent until rewritten — Ape-X already tolerates priorities that
        stale (the write-back ring lags them anyway)."""
        with self._lock:
            self.omega = float(omega)

    @property
    def max_n_step(self) -> int:
        """Largest n the ring geometry admits (constructor + set_n_step
        require seg > history + n) — league genomes clamp to this so an
        explore draw near the prior ceiling can never crash-loop a member
        into eviction."""
        return self.seg - self.history - 1

    def set_n_step(self, n_step: int) -> None:
        """Mid-run n-step adoption (league/ live gene, adopted at drain
        boundaries).  Assembly recomputes every window from raw per-step
        rewards, so EXISTING transitions re-read correctly under the new n
        — what changes is *eligibility*: which slots have a complete,
        cut-legal n-step future.  Eligibility is therefore recomputed for
        the whole ring (vectorised, one pass) instead of trusting marks
        made under the old n:

        - slots within n of the write cursor lose eligibility (future now
          incomplete) until the cursor moves past them — and since append
          only marks the slot exactly n back, slots in the old-n..new-n gap
          would otherwise stay marked with a short future;
        - slots whose NEW window hits a truncation before any terminal are
          fenced (the unbiased time-limit rule, re-applied under new n);
        - newly-eligible slots (n shrank) enter at ``max_priority``, the
          fresh-item default.
        """
        n = int(n_step)
        if n < 1:
            raise ValueError(f"n_step ({n}) must be >= 1")
        with self._lock:
            if n == self.n_step:
                return
            if self.seg <= self.history + n:
                raise ValueError(
                    f"per-lane segment {self.seg} too small for history "
                    f"{self.history} + n_step {n} — a smaller replay or a "
                    f"shorter n is required (league genomes must respect "
                    "the buffer geometry)")
            self.n_step = n
            self._gammas = self.gamma ** np.arange(n + 1, dtype=np.float32)
            self._refresh_eligibility_locked()

    def _refresh_eligibility_locked(self, chunk: int = 8192) -> None:
        """Recompute tree eligibility for every slot under the current
        n_step/history.  Vectorised in offset CHUNKS: the window gather is
        [lanes, chunk, n] — an Atari-scale ring (1M slots, n up to the
        genome prior's 10) would otherwise materialize ~100MB of transient
        index/bool arrays inside the buffer lock for one rare retune."""
        if self.filled == 0:
            return
        steps = np.arange(self.n_step)
        for lo in range(0, self.seg, chunk):
            offs = np.arange(lo, min(lo + chunk, self.seg))
            written = (np.ones(offs.size, bool) if self.filled >= self.seg
                       else offs < self.filled)
            # future complete: the newest written slot is (pos-1) % seg;
            # slot `off` needs n appends after it, i.e. age >= n
            future_ok = ((self.pos - 1 - offs) % self.seg) >= self.n_step
            # lookback dead zone: stacks ending here would cross the cursor
            look_dead = ((offs - self.pos) % self.seg) < self.history
            ok_off = written & future_ok & ~look_dead
            # unbiased time-limit rule under the NEW window: first cut
            # inside [off, off+n) being a truncation fences the slot
            w_offs = (offs[:, None] + steps[None, :]) % self.seg
            slots = (self._lane_base[:, None, None]
                     + w_offs[None, :, :])  # [L, chunk, n]
            cuts_w = self.cuts[slots]
            term_w = self.terminals[slots]
            first_cut = cuts_w.argmax(axis=2)
            has_cut = cuts_w.any(axis=2)
            first_is_trunc = ~np.take_along_axis(
                term_w, first_cut[..., None], axis=2)[..., 0]
            eligible = ok_off[None, :] & ~(has_cut & first_is_trunc)
            idx = (self._lane_base[:, None] + offs[None, :]).ravel()
            current = self.tree.get(idx)
            flat = eligible.ravel()
            self.tree.set(idx, np.where(
                flat, np.where(current > 0, current, self.max_priority),
                0.0))

    def append(self, frame, action, reward, terminal, priority=None) -> int:
        """Single-lane convenience (reference's per-process API shape)."""
        pri = None if priority is None else np.asarray([priority])
        return int(
            self.append_batch(
                np.asarray(frame)[None],
                np.asarray([action]),
                np.asarray([reward], np.float32),
                np.asarray([terminal]),
                pri,
            )[0]
        )

    def __len__(self) -> int:
        return self.filled * self.lanes

    @property
    def sampleable(self) -> bool:
        return self.tree.total > 0

    # ------------------------------------------------------------------ sample
    def _gather_stacks(self, lane: np.ndarray, off: np.ndarray) -> np.ndarray:
        """Frame stacks ending at lane-local offset `off`: [B, H, W, history].

        Frames from before the episode start (a terminal strictly inside the
        lookback window) are zeroed — the reference's reset-time zero-stack
        semantics without storing the zero frames.
        """
        B = off.shape[0]
        steps = np.arange(-(self.history - 1), 1)  # [-h+1 .. 0]
        offs = (off[:, None] + steps[None, :]) % self.seg  # [B, h]
        slots = lane[:, None] * self.seg + offs
        stacks = self.frames[slots]  # [B, h, H, W]

        # an episode cut at window position j (j < h-1) kills frames [.. j]
        term = self.cuts[slots[:, :-1]]  # [B, h-1]
        dead_tail = np.cumsum(term[:, ::-1], axis=1)[:, ::-1] > 0  # any terminal at/after j
        valid = np.concatenate([~dead_tail, np.ones((B, 1), bool)], axis=1)
        # frames older than what's been written in a young buffer are invalid too
        if self.filled < self.seg:
            age_ok = (off[:, None] + steps[None, :]) >= 0
            valid &= age_ok
        stacks = stacks * valid[:, :, None, None].astype(np.uint8)
        return np.moveaxis(stacks, 1, -1)  # [B, H, W, h]

    def sample(self, batch_size: int, beta: float) -> SampledBatch:
        """Stratified proportional sample + n-step assembly + IS weights."""
        hostsync.check_host_work("replay_sample")
        with self._lock:
            return self._sample_locked(batch_size, beta)

    def _sample_locked(self, batch_size: int, beta: float) -> SampledBatch:
        idx, prob = self.tree.sample_stratified(batch_size, self.rng)
        prob = np.maximum(prob, 1e-12)  # fp edge-fall can land on a zero leaf
        obs, next_obs, action, reward, discount = self._assemble_locked(idx)
        n = len(self)
        weights = (n * prob) ** (-beta)
        weights = (weights / weights.max()).astype(np.float32)
        return SampledBatch(
            idx=idx,
            obs=obs,
            action=action,
            reward=reward,
            next_obs=next_obs,
            discount=discount,
            weight=weights,
            prob=prob,
        )

    def assemble(self, idx: np.ndarray, out=None):
        """n-step assembly + stack gathers at already-drawn slot ids (the
        device-sampling gather path: the frontier drew ``idx`` on device;
        the host's job is this index-driven gather).  Returns
        ``(obs, next_obs, action, reward, discount)`` in ``idx`` order.
        ``out``, when given, receives the rows in place (contiguous row
        slices of a larger batch — zero-copy on the native core)."""
        idx = np.ascontiguousarray(np.asarray(idx, np.int64).ravel())
        if idx.size and (idx.min() < 0 or idx.max() >= self.capacity):
            # the native core would read out of bounds — fail loudly instead
            raise IndexError(
                f"assemble idx out of range [0, {self.capacity})"
            )
        with self._lock:
            return self._assemble_locked(idx, out)

    def _assemble_locked(self, idx: np.ndarray, out=None):
        batch_size = idx.shape[0]
        if self._core is not None:
            # v2: n-step scan + both stack gathers in one native call
            return self._core.assemble(idx, batch_size, out=out)
        lane = idx // self.seg
        off = idx % self.seg

        # --- n-step scan (vectorised over the batch) ---------------------
        steps = np.arange(self.n_step)
        f_offs = (off[:, None] + steps[None, :]) % self.seg  # [B, n]
        f_slots = lane[:, None] * self.seg + f_offs
        r = self.rewards[f_slots]  # [B, n]
        d = self.terminals[f_slots]  # [B, n]
        # alive[k] = no terminal strictly before step k
        alive = np.cumprod(1.0 - d[:, :-1].astype(np.float32), axis=1)
        alive = np.concatenate([np.ones((batch_size, 1), np.float32), alive], axis=1)
        reward = (r * alive * self._gammas[None, : self.n_step]).sum(axis=1)
        done_within = d.any(axis=1)
        discount = np.where(done_within, 0.0, self._gammas[self.n_step]).astype(
            np.float32
        )

        obs = self._gather_stacks(lane, off)
        next_obs = self._gather_stacks(lane, (off + self.n_step) % self.seg)
        action = self.actions[lane * self.seg + off]
        reward = reward.astype(np.float32)
        if out is not None:  # NumPy fallback: one copy into the caller rows
            out[0][:] = obs
            out[1][:] = next_obs
            out[2][:] = action
            out[3][:] = reward
            out[4][:] = discount
            return out
        return (obs, next_obs, action, reward, discount)

    # -------------------------------------------------------------- snapshot
    def snapshot(self, path: str) -> None:
        """Persist the full replay state (parity: the reference's replay
        survives via Redis RDB/AOF persistence, SURVEY.md §5 'Checkpoint';
        here one compressed npz per shard)."""
        with self._lock:
            self._snapshot_locked(path)

    def _snapshot_locked(self, path: str) -> None:
        import json

        from rainbow_iqn_apex_tpu.replay import snapshot_io

        snapshot_io.atomic_savez(
            path,
            frames=self.frames,
            actions=self.actions,
            rewards=self.rewards,
            terminals=self.terminals,
            cuts=self.cuts,
            tree=self.tree.tree,
            pos=self.pos,
            filled=self.filled,
            max_priority=self.max_priority,
            # sampler RNG state: exact resume must replay the SAME batch the
            # uninterrupted run would have drawn (preemption-safe resume)
            rng_state=np.frombuffer(
                json.dumps(self.rng.bit_generator.state).encode(), np.uint8
            ),
        )

    def restore(self, path: str) -> None:
        from rainbow_iqn_apex_tpu.replay import snapshot_io

        self.apply_snapshot(snapshot_io.load(path))

    def apply_snapshot(self, z) -> None:
        """Apply an already-loaded (and CRC-verified) snapshot payload —
        lets ShardedReplay verify every shard first and apply without
        re-reading the files."""
        if z["frames"].shape != self.frames.shape:
            raise ValueError(
                f"snapshot shape {z['frames'].shape} != buffer {self.frames.shape}"
            )
        self.frames[:] = z["frames"]
        self.actions[:] = z["actions"]
        self.rewards[:] = z["rewards"]
        self.terminals[:] = z["terminals"]
        # older snapshots (pre two-channel) carry no cuts array
        self.cuts[:] = z["cuts"] if "cuts" in z.files else z["terminals"]
        self.tree.tree[:] = z["tree"]
        self.pos = int(z["pos"])
        self.filled = int(z["filled"])
        self.max_priority = float(z["max_priority"])
        if "rng_state" in z.files:  # pre-resilience snapshots carry no RNG
            import json

            self.rng.bit_generator.state = json.loads(
                np.asarray(z["rng_state"], np.uint8).tobytes().decode()
            )

    # -------------------------------------------------------------- priorities
    def update_priorities(self, idx: np.ndarray, td_abs: np.ndarray) -> None:
        """Learner write-back: p = (|TD| + eps)^omega (reference semantics)."""
        with self._lock:
            pri = (np.asarray(td_abs, np.float64) + self.eps) ** self.omega
            self.max_priority = max(self.max_priority, float(pri.max()))
            # Never resurrect slots the cursor has since invalidated.
            current = self.tree.get(np.asarray(idx))
            pri = np.where(current > 0, pri, 0.0)
            self.tree.set(idx, pri)
