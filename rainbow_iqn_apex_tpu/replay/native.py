"""ctypes binding for the C++ replay core (replay/native/*.cc).

v1: sum-tree set/find hot loops (sumtree.cc).  v2 adds the fused per-tick
append and per-batch assembly paths (replay_core.cc).  Builds one shared
library on first use with g++ (toolchain is baked into the image; no
pip/pybind11 needed) and caches it next to the sources.  Falls back silently
to the NumPy implementation when no compiler is available —
``native_available()`` is the gate.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

from rainbow_iqn_apex_tpu.replay.sumtree import SumTree

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRCS = (
    os.path.join(_HERE, "native", "sumtree.cc"),
    os.path.join(_HERE, "native", "replay_core.cc"),
)


def _so_path() -> str:
    """Cache path keyed by source hash: a stale or foreign-host binary (built
    with -march=native elsewhere) is never loaded — any source change or
    fresh checkout gets its own artifact name and triggers a rebuild."""
    import hashlib

    h = hashlib.sha256()
    for src in _SRCS:
        with open(src, "rb") as f:
            h.update(f.read())
    return os.path.join(_HERE, "native", f"_replay_{h.hexdigest()[:16]}.so")


_SO = _so_path()

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False

_i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
_f64p = np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS")
_f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
_i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
_u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")


def _build_and_load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        try:
            if not os.path.exists(_SO):  # name is content-hashed: exists == fresh
                subprocess.run(
                    ["g++", "-O3", "-march=native", "-shared", "-fPIC", *_SRCS,
                     "-o", _SO],
                    check=True,
                    capture_output=True,
                    timeout=120,
                )
            lib = ctypes.CDLL(_SO)
            lib.st_set.argtypes = [_f64p, ctypes.c_int64, _i64p, _f64p, ctypes.c_int64]
            lib.st_set.restype = None
            lib.st_find_prefix.argtypes = [
                _f64p, ctypes.c_int64, ctypes.c_int64, _f64p, _i64p, ctypes.c_int64,
            ]
            lib.st_find_prefix.restype = None
            lib.st_sample.argtypes = [
                _f64p, ctypes.c_int64, ctypes.c_int64, _f64p, _i64p, _f64p,
                ctypes.c_int64,
            ]
            lib.st_sample.restype = None
            i64 = ctypes.c_int64
            lib.rb_append_tick.argtypes = [
                _u8p, _i32p, _f32p, _u8p, _u8p,  # frames/actions/rewards/term/cuts
                _f64p, i64,  # tree, span
                i64, i64, i64, i64, i64, i64, i64,  # lanes seg pos filled hist n fb
                _u8p, _i32p, _f32p, _u8p,  # new frame/action/reward/terminal
                ctypes.c_void_p, ctypes.c_void_p,  # truncs?, priorities?
                ctypes.c_double, ctypes.c_double,  # eps, omega
                ctypes.POINTER(ctypes.c_double),  # max_priority (inout)
            ]
            lib.rb_append_tick.restype = None
            lib.rb_assemble.argtypes = [
                _u8p, _i32p, _f32p, _u8p, _u8p,
                i64, i64, i64, i64, i64,  # seg filled hist n fb
                _f32p,  # gammas
                _i64p, i64,  # idx, batch
                _u8p, _u8p, _i32p, _f32p, _f32p,  # outputs
            ]
            lib.rb_assemble.restype = None
            _lib = lib
        except Exception:
            _lib = None
        return _lib


def native_available() -> bool:
    return _build_and_load() is not None


class NativeSumTree(SumTree):
    """Drop-in SumTree with the set/find hot loops in C++.

    Same flat-array layout and numerics as the NumPy SumTree (the fuzz test
    runs both against each other); storage stays a NumPy array so snapshots
    and the rest of the Python API are unchanged.
    """

    def __init__(self, capacity: int):
        super().__init__(capacity)
        self._lib = _build_and_load()
        if self._lib is None:
            raise RuntimeError("native sum-tree unavailable (no compiler?)")

    def set(self, idx: np.ndarray, priority: np.ndarray) -> None:
        idx = np.ascontiguousarray(np.asarray(idx, np.int64).ravel())
        pri = np.ascontiguousarray(
            np.broadcast_to(np.asarray(priority, np.float64).ravel(), idx.shape)
        )
        if idx.size == 0:
            return
        if np.any(pri < 0) or not np.all(np.isfinite(pri)):
            raise ValueError("priorities must be finite and non-negative")
        self._lib.st_set(self.tree, self.span, idx, pri, idx.size)

    def find_prefix(self, mass: np.ndarray) -> np.ndarray:
        mass = np.ascontiguousarray(np.asarray(mass, np.float64).ravel())
        out = np.empty(mass.size, np.int64)
        self._lib.st_find_prefix(self.tree, self.span, self.capacity, mass, out, mass.size)
        return out

    def sample_stratified(
        self, batch_size: int, rng: np.random.Generator
    ) -> Tuple[np.ndarray, np.ndarray]:
        total = self.total
        if total <= 0:
            raise ValueError("cannot sample from an empty tree")
        seg = total / batch_size
        mass = np.ascontiguousarray(
            (np.arange(batch_size) + rng.random(batch_size)) * seg
        )
        idx = np.empty(batch_size, np.int64)
        pri = np.empty(batch_size, np.float64)
        self._lib.st_sample(self.tree, self.span, self.capacity, mass, idx, pri, batch_size)
        return idx, pri / total


class ReplayCore:
    """v2 fused append/assemble over a PrioritizedReplay's own arrays.

    One ctypes call per actor tick (ring writes + every tree update,
    including the truncation-eligibility rule) and one per sampled batch
    (n-step scan + both stack gathers straight into the [B, H, W, hist]
    device layout).  The buffer's NumPy arrays are the single source of
    truth; this object holds no state beyond the library handle.
    """

    def __init__(self, buf):
        self._lib = _build_and_load()
        if self._lib is None:
            raise RuntimeError("native replay core unavailable (no compiler?)")
        self._b = buf
        self._fb = buf.frames.shape[1] * buf.frames.shape[2]

    def append_tick(self, frames, actions, rewards, terminals, priorities,
                    truncations) -> float:
        b = self._b
        mp = ctypes.c_double(b.max_priority)
        trunc = (
            None
            if truncations is None
            else np.ascontiguousarray(np.asarray(truncations, bool)).view(np.uint8)
        )
        pri = (
            None
            if priorities is None
            else np.ascontiguousarray(np.asarray(priorities, np.float64))
        )
        self._lib.rb_append_tick(
            b.frames.reshape(b.frames.shape[0], -1),
            b.actions, b.rewards,
            b.terminals.view(np.uint8), b.cuts.view(np.uint8),
            b.tree.tree, b.tree.span,
            b.lanes, b.seg, b.pos, b.filled, b.history, b.n_step, self._fb,
            np.ascontiguousarray(frames, np.uint8).reshape(len(frames), -1),
            np.ascontiguousarray(actions, np.int32),
            np.ascontiguousarray(rewards, np.float32),
            np.ascontiguousarray(np.asarray(terminals, bool)).view(np.uint8),
            None if trunc is None else trunc.ctypes.data_as(ctypes.c_void_p),
            None if pri is None else pri.ctypes.data_as(ctypes.c_void_p),
            b.eps, b.omega, ctypes.byref(mp),
        )
        return mp.value

    def assemble(self, idx: np.ndarray, batch_size: int, out=None):
        """``out`` (obs, next_obs, action, reward, discount), when given,
        receives the rows in place — C-contiguous row slices of a caller's
        batch buffers are accepted, so a shard-sorted gather (the device
        sample frontier's draw returns slot-sorted indices) fills the final
        batch with ZERO extra copies."""
        b = self._b
        h, w = b.frames.shape[1], b.frames.shape[2]
        if out is None:
            obs = np.empty((batch_size, h, w, b.history), np.uint8)
            next_obs = np.empty_like(obs)
            action = np.empty(batch_size, np.int32)
            reward = np.empty(batch_size, np.float32)
            discount = np.empty(batch_size, np.float32)
        else:
            obs, next_obs, action, reward, discount = out
        self._lib.rb_assemble(
            b.frames.reshape(b.frames.shape[0], -1),
            b.actions, b.rewards,
            b.terminals.view(np.uint8), b.cuts.view(np.uint8),
            b.seg, b.filled, b.history, b.n_step, self._fb,
            b._gammas,
            np.ascontiguousarray(idx, np.int64), batch_size,
            obs.reshape(batch_size, -1), next_obs.reshape(batch_size, -1),
            action, reward, discount,
        )
        return obs, next_obs, action, reward, discount
