"""ctypes binding for the C++ sum-tree core (replay/native/sumtree.cc).

Builds the shared library on first use with g++ (toolchain is baked into the
image; no pip/pybind11 needed) and caches it next to the source.  Falls back
silently to the NumPy implementation when no compiler is available —
``native_available()`` is the gate.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

from rainbow_iqn_apex_tpu.replay.sumtree import SumTree

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "native", "sumtree.cc")


def _so_path() -> str:
    """Cache path keyed by source hash: a stale or foreign-host binary (built
    with -march=native elsewhere) is never loaded — any source change or
    fresh checkout gets its own artifact name and triggers a rebuild."""
    import hashlib

    with open(_SRC, "rb") as f:
        h = hashlib.sha256(f.read()).hexdigest()[:16]
    return os.path.join(_HERE, "native", f"_sumtree_{h}.so")


_SO = _so_path()

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False

_i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
_f64p = np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS")


def _build_and_load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        try:
            if not os.path.exists(_SO):  # name is content-hashed: exists == fresh
                subprocess.run(
                    ["g++", "-O3", "-march=native", "-shared", "-fPIC", _SRC, "-o", _SO],
                    check=True,
                    capture_output=True,
                    timeout=120,
                )
            lib = ctypes.CDLL(_SO)
            lib.st_set.argtypes = [_f64p, ctypes.c_int64, _i64p, _f64p, ctypes.c_int64]
            lib.st_set.restype = None
            lib.st_find_prefix.argtypes = [
                _f64p, ctypes.c_int64, ctypes.c_int64, _f64p, _i64p, ctypes.c_int64,
            ]
            lib.st_find_prefix.restype = None
            lib.st_sample.argtypes = [
                _f64p, ctypes.c_int64, ctypes.c_int64, _f64p, _i64p, _f64p,
                ctypes.c_int64,
            ]
            lib.st_sample.restype = None
            _lib = lib
        except Exception:
            _lib = None
        return _lib


def native_available() -> bool:
    return _build_and_load() is not None


class NativeSumTree(SumTree):
    """Drop-in SumTree with the set/find hot loops in C++.

    Same flat-array layout and numerics as the NumPy SumTree (the fuzz test
    runs both against each other); storage stays a NumPy array so snapshots
    and the rest of the Python API are unchanged.
    """

    def __init__(self, capacity: int):
        super().__init__(capacity)
        self._lib = _build_and_load()
        if self._lib is None:
            raise RuntimeError("native sum-tree unavailable (no compiler?)")

    def set(self, idx: np.ndarray, priority: np.ndarray) -> None:
        idx = np.ascontiguousarray(np.asarray(idx, np.int64).ravel())
        pri = np.ascontiguousarray(
            np.broadcast_to(np.asarray(priority, np.float64).ravel(), idx.shape)
        )
        if idx.size == 0:
            return
        if np.any(pri < 0) or not np.all(np.isfinite(pri)):
            raise ValueError("priorities must be finite and non-negative")
        self._lib.st_set(self.tree, self.span, idx, pri, idx.size)

    def find_prefix(self, mass: np.ndarray) -> np.ndarray:
        mass = np.ascontiguousarray(np.asarray(mass, np.float64).ravel())
        out = np.empty(mass.size, np.int64)
        self._lib.st_find_prefix(self.tree, self.span, self.capacity, mass, out, mass.size)
        return out

    def sample_stratified(
        self, batch_size: int, rng: np.random.Generator
    ) -> Tuple[np.ndarray, np.ndarray]:
        total = self.total
        if total <= 0:
            raise ValueError("cannot sample from an empty tree")
        seg = total / batch_size
        mass = np.ascontiguousarray(
            (np.arange(batch_size) + rng.random(batch_size)) * seg
        )
        idx = np.empty(batch_size, np.int64)
        pri = np.empty(batch_size, np.float64)
        self._lib.st_sample(self.tree, self.span, self.capacity, mass, idx, pri, batch_size)
        return idx, pri / total
