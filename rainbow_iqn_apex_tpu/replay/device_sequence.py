"""Device-resident stored-state sequence replay (the R2D2 twin of
replay/device.py).

Same semantics as the host SequenceReplay (replay/sequence.py) — per-lane
builders chopping episode streams into overlapping fixed-length sequences
with the actor's LSTM state at each window start, two-channel cuts (flush on
terminal OR truncation, `done` only for true terminals), max-priority
insertion, eta-mix write-back — but the ring, the builders, and prioritized
sampling all live in HBM as one pytree, so the fused R2D2 Anakin tick
(act -> env.step -> append -> learn) compiles into a single XLA graph.

The one structural difference from the host version: the number of sequences
EMITTED per tick is data-dependent (a lane emits when its builder fills or
its episode cuts), which XLA cannot express as a dynamic store count.  The
ring therefore carries ONE scratch row (index C): every lane scatters its
builder window somewhere each tick — emitting lanes to `(pos + rank) % C`
(rank = that lane's position among this tick's emitters), non-emitting lanes
to the scratch row — so shapes stay static and the write is one batched
scatter.  Sampling and priorities only ever see rows [0, C).
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import chex
import jax
import jax.numpy as jnp

from rainbow_iqn_apex_tpu.ops.r2d2 import SequenceBatch


class DeviceSeqState(NamedTuple):
    # sequence ring, one scratch row at index C
    frames: jnp.ndarray  # [C+1, L, H, W] uint8
    actions: jnp.ndarray  # [C+1, L] int32
    rewards: jnp.ndarray  # [C+1, L] f32
    dones: jnp.ndarray  # [C+1, L] bool
    valids: jnp.ndarray  # [C+1, L] bool
    init_c: jnp.ndarray  # [C+1, lstm] f32
    init_h: jnp.ndarray  # [C+1, lstm] f32
    priority: jnp.ndarray  # [C] f32 (already ^omega, like the host tree)
    pos: jnp.ndarray  # scalar i32 — next ring slot
    filled: jnp.ndarray  # scalar i32
    max_priority: jnp.ndarray  # scalar f32
    # per-lane builders
    buf_frames: jnp.ndarray  # [lanes, L, H, W] uint8
    buf_actions: jnp.ndarray  # [lanes, L] i32
    buf_rewards: jnp.ndarray  # [lanes, L] f32
    buf_dones: jnp.ndarray  # [lanes, L] bool
    buf_c: jnp.ndarray  # [lanes, L, lstm] f32
    buf_h: jnp.ndarray  # [lanes, L, lstm] f32
    buf_len: jnp.ndarray  # [lanes] i32


class DeviceSequenceReplay:
    """Pure-functional sequence replay: all methods are jit-safe
    (state, ...) -> state transforms over a DeviceSeqState pytree."""

    def __init__(
        self,
        capacity: int,
        seq_len: int,
        frame_shape: Tuple[int, int],
        lstm_size: int,
        lanes: int,
        stride: Optional[int] = None,
        priority_exponent: float = 0.9,
        priority_eps: float = 1e-6,
    ):
        if stride is not None and not (0 < stride <= seq_len):
            raise ValueError("stride must be in (0, seq_len]")
        if capacity < lanes:
            raise ValueError(
                f"capacity ({capacity}) must be >= lanes ({lanes}): every "
                "lane can emit a sequence on the same tick"
            )
        self.capacity = capacity
        self.L = seq_len
        self.lanes = lanes
        self.stride = stride or max(seq_len // 2, 1)
        self.omega = priority_exponent
        self.eps = priority_eps
        self.frame_shape = frame_shape
        self.lstm_size = lstm_size

    def init_state(self) -> DeviceSeqState:
        C, L, (h, w), m, lanes = (
            self.capacity, self.L, self.frame_shape, self.lstm_size, self.lanes,
        )
        return DeviceSeqState(
            frames=jnp.zeros((C + 1, L, h, w), jnp.uint8),
            actions=jnp.zeros((C + 1, L), jnp.int32),
            rewards=jnp.zeros((C + 1, L), jnp.float32),
            dones=jnp.zeros((C + 1, L), bool),
            valids=jnp.zeros((C + 1, L), bool),
            init_c=jnp.zeros((C + 1, m), jnp.float32),
            init_h=jnp.zeros((C + 1, m), jnp.float32),
            priority=jnp.zeros((C,), jnp.float32),
            pos=jnp.int32(0),
            filled=jnp.int32(0),
            max_priority=jnp.float32(1.0),
            buf_frames=jnp.zeros((lanes, L, h, w), jnp.uint8),
            buf_actions=jnp.zeros((lanes, L), jnp.int32),
            buf_rewards=jnp.zeros((lanes, L), jnp.float32),
            buf_dones=jnp.zeros((lanes, L), bool),
            buf_c=jnp.zeros((lanes, L, m), jnp.float32),
            buf_h=jnp.zeros((lanes, L, m), jnp.float32),
            buf_len=jnp.zeros((lanes,), jnp.int32),
        )

    # ------------------------------------------------------------- appending
    def append(
        self,
        s: DeviceSeqState,
        frames: jnp.ndarray,  # [lanes, H, W] uint8 — frame the action saw
        actions: jnp.ndarray,  # [lanes] i32
        rewards: jnp.ndarray,  # [lanes] f32
        terminals: jnp.ndarray,  # [lanes] bool — TRUE terminals only
        truncations: jnp.ndarray,  # [lanes] bool — time-limit cuts
        lstm_c: jnp.ndarray,  # [lanes, lstm] actor state BEFORE this step
        lstm_h: jnp.ndarray,
    ) -> DeviceSeqState:
        """One lockstep tick of all lanes (mirror of _append_locked,
        replay/sequence.py): builder scatter, then emit full/cut windows into
        the ring via the scratch-row batched scatter, then carry-over."""
        lanes, L, C, stride = self.lanes, self.L, self.capacity, self.stride
        lane = jnp.arange(lanes)
        k = s.buf_len  # [lanes] write offsets, in [0, L-1]

        bf = s.buf_frames.at[lane, k].set(frames)
        ba = s.buf_actions.at[lane, k].set(actions.astype(jnp.int32))
        br = s.buf_rewards.at[lane, k].set(rewards.astype(jnp.float32))
        bd = s.buf_dones.at[lane, k].set(terminals)
        bc = s.buf_c.at[lane, k].set(lstm_c.astype(jnp.float32))
        bh = s.buf_h.at[lane, k].set(lstm_h.astype(jnp.float32))
        klen = k + 1  # post-write lengths

        cut = terminals | truncations
        emit = cut | (klen == L)

        # ring slots: emitters take pos+rank (mod C), others the scratch row
        rank = jnp.cumsum(emit.astype(jnp.int32)) - 1
        n_emit = emit.sum().astype(jnp.int32)
        slots = jnp.where(emit, (s.pos + rank) % C, C)

        steps = jnp.arange(L)
        valid_mask = steps[None, :] < klen[:, None]  # [lanes, L]

        def zpad(buf, mask):
            return jnp.where(mask, buf, jnp.zeros_like(buf))

        vm = valid_mask
        frames_row = zpad(bf, vm[..., None, None])
        actions_row = zpad(ba, vm)
        rewards_row = zpad(br, vm)
        dones_row = zpad(bd, vm)

        st = s._replace(
            buf_frames=bf, buf_actions=ba, buf_rewards=br, buf_dones=bd,
            buf_c=bc, buf_h=bh,
        )
        st = st._replace(
            frames=st.frames.at[slots].set(frames_row),
            actions=st.actions.at[slots].set(actions_row),
            rewards=st.rewards.at[slots].set(rewards_row),
            dones=st.dones.at[slots].set(dones_row),
            valids=st.valids.at[slots].set(vm),
            init_c=st.init_c.at[slots].set(bc[:, 0]),
            init_h=st.init_h.at[slots].set(bh[:, 0]),
        )
        # max-priority insertion for emitted slots (clip scratch writes away
        # by scattering into a length-C+1 view and dropping the tail)
        pri_ext = jnp.concatenate([st.priority, jnp.zeros((1,), jnp.float32)])
        pri_ext = pri_ext.at[slots].set(
            jnp.where(emit, st.max_priority, pri_ext[slots])
        )
        st = st._replace(
            priority=pri_ext[:C],
            pos=(s.pos + n_emit) % C,
            filled=jnp.minimum(s.filled + n_emit, C),
        )

        # ---- builder carry-over -------------------------------------------
        # flush (cut): restart empty.  full (no cut): keep last L-stride
        # steps.  neither: just the incremented length.
        tail = L - stride
        shifted = jax.tree.map(
            lambda b: jnp.roll(b, -stride, axis=1),
            (bf, ba, br, bd, bc, bh),
        )

        def pick(orig, shift):
            sel = emit & ~cut  # overlap carry-over
            sh = jnp.reshape(sel, (lanes,) + (1,) * (orig.ndim - 1))
            return jnp.where(sh, shift, orig)

        bf2, ba2, br2, bd2, bc2, bh2 = (
            pick(o, sh) for o, sh in zip((bf, ba, br, bd, bc, bh), shifted)
        )
        new_len = jnp.where(cut, 0, jnp.where(emit, tail, klen))
        return st._replace(
            buf_frames=bf2, buf_actions=ba2, buf_rewards=br2, buf_dones=bd2,
            buf_c=bc2, buf_h=bh2, buf_len=new_len.astype(jnp.int32),
        )

    # -------------------------------------------------------------- sampling
    def _effective_priority(self, s: DeviceSeqState) -> jnp.ndarray:
        """Cold-ring guard: when every priority is zero (empty ring, or a
        ring whose only writes were scratch-row misses), degrade to a uniform
        draw over the filled prefix — never the degenerate always-slot-0 draw
        a zero cdf would produce.  Trainers still must warm-gate learning
        (see build_device_r2d2_learn); this guard bounds the damage if one
        doesn't."""
        p = s.priority
        uniform = (
            jnp.arange(p.shape[0]) < jnp.maximum(s.filled, 1)
        ).astype(jnp.float32)
        return jnp.where(p.sum() > 0.0, p, uniform)

    def draw(self, s: DeviceSeqState, key: chex.PRNGKey,
             batch_size: int) -> jnp.ndarray:
        """Stratified proportional draw over ring priorities (mirror of
        SumTree.sample_stratified)."""
        p = self._effective_priority(s)
        total = p.sum()
        cdf = jnp.cumsum(p)
        u = (jnp.arange(batch_size) + jax.random.uniform(key, (batch_size,)))
        u = u / batch_size * total
        return jnp.clip(
            jnp.searchsorted(cdf, u, side="right"), 0, p.shape[0] - 1
        ).astype(jnp.int32)

    def assemble(
        self, s: DeviceSeqState, idx: jnp.ndarray, beta: jnp.ndarray,
        *, with_weight: bool = True,
    ) -> Tuple[SequenceBatch, jnp.ndarray]:
        """Gather sequences + IS weights at slot ids.  Returns
        (SequenceBatch with [B, L, H, W, 1] obs, prob [B]).

        ``with_weight=False`` returns batch.weight as ones for callers that
        derive a globally consistent weight from ``prob`` instead (the
        sharded learner's psum/pmax mixture formula)."""
        p = self._effective_priority(s)
        total = p.sum()
        prob = jnp.maximum(p[idx] / jnp.maximum(total, 1e-12), 1e-12)
        if with_weight:
            w = (jnp.maximum(s.filled, 1).astype(jnp.float32) * prob) ** (-beta)
            weight = w / w.max()
        else:
            weight = jnp.ones_like(prob)
        batch = SequenceBatch(
            obs=s.frames[idx][..., None],
            action=s.actions[idx],
            reward=s.rewards[idx],
            done=s.dones[idx],
            valid=s.valids[idx],
            init_c=s.init_c[idx],
            init_h=s.init_h[idx],
            weight=weight,
        )
        return batch, prob

    def sample_grouped(
        self, s: DeviceSeqState, key: chex.PRNGKey, batch_size: int,
        groups: int, beta: jnp.ndarray,
    ) -> Tuple[jnp.ndarray, SequenceBatch, jnp.ndarray]:
        """``groups`` independent stratified draws of ``batch_size``
        sequences concatenated into one [G*B] learn batch — the sequence
        twin of replay/device.DeviceReplay.sample_grouped (cfg.sample_groups,
        the TPU batch-scaling knob): per-group stratum width and per-group
        max-normalised IS weights, exactly as G sequential reference steps.

        Returns (idx [G, B], SequenceBatch over [G*B], prob [G*B])."""
        keys = jax.random.split(key, groups)
        idx = jax.vmap(lambda k: self.draw(s, k, batch_size))(keys)
        batch, prob = self.assemble(s, idx.reshape(-1), beta,
                                    with_weight=False)
        w = (jnp.maximum(s.filled, 1).astype(jnp.float32) * prob) ** (-beta)
        w = w.reshape(groups, batch_size)
        w = w / w.max(axis=1, keepdims=True)
        return idx, batch.replace(weight=w.reshape(-1)), prob

    # ------------------------------------------------------------- priorities
    def update_priorities(
        self, s: DeviceSeqState, idx: jnp.ndarray, td_mix: jnp.ndarray
    ) -> DeviceSeqState:
        """Learner eta-mix write-back (mirror of SequenceReplay
        .update_priorities: direct set, running max)."""
        pri = (td_mix.astype(jnp.float32) + self.eps) ** self.omega
        return s._replace(
            priority=s.priority.at[idx].set(pri),
            max_priority=jnp.maximum(s.max_priority, pri.max()),
        )

    def update_priorities_grouped(
        self, s: DeviceSeqState, idx: jnp.ndarray, td_mix: jnp.ndarray
    ) -> DeviceSeqState:
        """Write-back for sample_grouped's [G, B] indices in group order
        (last group wins on duplicates, as G sequential steps would)."""
        G = idx.shape[0]
        td = td_mix.reshape(G, -1)
        for g in range(G):
            s = self.update_priorities(s, idx[g], td[g])
        return s


def build_device_r2d2_learn(cfg, num_actions: int,
                            replay: DeviceSequenceReplay):
    """The fused R2D2 learner tick: draw -> assemble -> sequence learn step
    -> eta-mix priority write-back, one jittable pure function
    (train_state, replay_state, key, beta) -> (train_state, replay_state,
    info) — the recurrent twin of replay/device.build_device_learn.

    WARM-GATE CONTRACT: callers must not invoke this until the ring holds a
    meaningful population (the trainers gate on
    ``filled >= max(learn_start // seq_total, 8)``, train_anakin_r2d2.py /
    train_r2d2.py parity).  A cold ring degrades draw() to uniform-over-
    filled (see _effective_priority) rather than corrupting training, but
    the early gradients would still be on near-empty windows."""
    from rainbow_iqn_apex_tpu.ops.r2d2 import build_r2d2_learn_step

    learn_step = build_r2d2_learn_step(cfg, num_actions)
    groups = getattr(cfg, "sample_groups", 1)

    def fused(train_state, replay_state, key, beta):
        k_sample, k_learn = jax.random.split(key)
        if groups > 1:
            idx, batch, _prob = replay.sample_grouped(
                replay_state, k_sample, cfg.batch_size, groups, beta
            )
            train_state, info = learn_step(train_state, batch, k_learn)
            replay_state = replay.update_priorities_grouped(
                replay_state, idx, info["priorities"]
            )
        else:
            idx = replay.draw(replay_state, k_sample, cfg.batch_size)
            batch, _prob = replay.assemble(replay_state, idx, beta)
            train_state, info = learn_step(train_state, batch, k_learn)
            replay_state = replay.update_priorities(
                replay_state, idx, info["priorities"]
            )
        return train_state, replay_state, info

    return fused


# ---------------------------------------------------------------------------
# dp-sharded variant: per-shard rings under shard_map (the sequence twin of
# replay/device.build_device_learn_sharded)
# ---------------------------------------------------------------------------


def stack_seq_shards(local_state: DeviceSeqState, n_dev: int) -> DeviceSeqState:
    """The sharded-sequence state layout: every leaf of the per-shard
    DeviceSeqState gains a leading device dim of size n_dev ("stacked
    shards"), sharded P(axis) on dim 0.  Unlike the transition replay —
    whose lockstep appends keep one REPLICATED cursor valid for all lanes —
    sequence emission counts are data-dependent per lane group, so every
    shard needs its own pos/filled/max_priority; stacking makes those
    per-shard scalars one [n_dev] array like everything else."""
    return jax.tree.map(
        lambda x: jnp.broadcast_to(x[None], (n_dev, *x.shape)), local_state
    )


def device_seq_specs(axis: str = "dp"):
    """PartitionSpecs for a stacked-shard DeviceSeqState (see
    stack_seq_shards): every leaf sharded over its leading device dim."""
    P = jax.sharding.PartitionSpec
    return jax.tree.map(lambda _: P(axis), DeviceSeqState(*DeviceSeqState._fields))


def device_seq_shardings(mesh, axis: str = "dp"):
    P = jax.sharding.PartitionSpec
    return jax.tree.map(
        lambda s: jax.sharding.NamedSharding(mesh, s),
        device_seq_specs(axis),
        is_leaf=lambda x: isinstance(x, P),
    )


def _shard_map():
    try:
        return jax.shard_map
    except AttributeError:  # pragma: no cover — older jax
        from jax.experimental.shard_map import shard_map

        return shard_map


def _unstack(gs: DeviceSeqState) -> DeviceSeqState:
    return jax.tree.map(lambda x: x[0], gs)


def _restack(s: DeviceSeqState) -> DeviceSeqState:
    return jax.tree.map(lambda x: x[None], s)


def build_sharded_seq_append(replay: DeviceSequenceReplay, mesh,
                             axis: str = "dp"):
    """shard_map'd append over stacked-shard state: each device's lane group
    emits into ITS OWN ring (rank/cumsum/pos all shard-local), so the
    batched scatter never crosses devices.  Inputs are [total_lanes, ...]
    arrays lane-sharded over `axis`; `replay` is configured with the
    PER-DEVICE lane count and capacity."""
    P = jax.sharding.PartitionSpec
    state_spec = device_seq_specs(axis)
    smap = _shard_map()

    def _append(gs, frames, actions, rewards, terms, truncs, c, h):
        s = replay.append(_unstack(gs), frames, actions, rewards, terms,
                          truncs, c, h)
        return _restack(s)

    return smap(
        _append, mesh=mesh,
        in_specs=(state_spec, P(axis), P(axis), P(axis), P(axis), P(axis),
                  P(axis), P(axis)),
        out_specs=state_spec,
    )


def build_device_r2d2_learn_sharded(cfg, num_actions: int,
                                    local_replay: DeviceSequenceReplay, mesh,
                                    axis: str = "dp"):
    """Multi-chip fused R2D2 learner: per-shard sequence rings, per-shard
    draws of batch/n sequences, one dp-sharded recurrent learn step.

    Because each shard contributes exactly batch/n draws regardless of how
    full it is, global sampling is a uniform mixture over shards:
    q(i) = prob_local(i) / n_dev.  Sequence emission is data-dependent, so
    shard fills genuinely differ — N_global is a real psum over per-shard
    fills (not the transition replay's symmetric filled * n shortcut) and IS
    weights are pmax-normalised across shards.  The gradient all-reduce
    stays GSPMD-inserted from the batch sharding."""
    from rainbow_iqn_apex_tpu.ops.r2d2 import SequenceBatch, build_r2d2_learn_step

    P = jax.sharding.PartitionSpec
    n_dev = mesh.shape[axis]
    if cfg.batch_size % n_dev:
        raise ValueError(
            f"batch {cfg.batch_size} not divisible by {n_dev} devices"
        )
    b_loc = cfg.batch_size // n_dev
    groups = getattr(cfg, "sample_groups", 1)
    learn_step = build_r2d2_learn_step(cfg, num_actions)
    state_spec = device_seq_specs(axis)
    batch_spec = SequenceBatch(
        obs=P(axis), action=P(axis), reward=P(axis), done=P(axis),
        valid=P(axis), init_c=P(axis), init_h=P(axis), weight=P(axis),
    )
    smap = _shard_map()

    def _draw_assemble(gs, key, beta):
        """Per-shard fixed-quota draw; cfg.sample_groups > 1 draws G groups
        of b_loc per shard (flattened, group g contiguous) with IS weights
        pmax-normalised PER GROUP — the grouped pattern of
        replay/device.build_device_learn_sharded over the psum'd sequence
        fill counts."""
        s = _unstack(gs)
        k = jax.random.fold_in(key, jax.lax.axis_index(axis))
        if groups > 1:
            keys = jax.random.split(k, groups)
            idx = jax.vmap(
                lambda kk: local_replay.draw(s, kk, b_loc)
            )(keys).reshape(-1)
        else:
            idx = local_replay.draw(s, k, b_loc)
        batch, prob = local_replay.assemble(s, idx, beta, with_weight=False)
        n_global = jax.lax.psum(s.filled, axis).astype(jnp.float32)
        nq = jnp.maximum(jnp.maximum(n_global, 1.0) * prob / n_dev, 1e-12)
        w = nq ** (-beta)
        wg = w.reshape(groups, b_loc)
        wmax = jax.lax.pmax(wg.max(axis=1), axis)
        w = (wg / wmax[:, None]).reshape(-1)
        return idx, batch.replace(weight=w)

    def _write_back(gs, idx, td_mix):
        s = _unstack(gs)
        if groups > 1:
            s = local_replay.update_priorities_grouped(
                s, idx.reshape(groups, b_loc), td_mix
            )
        else:
            s = local_replay.update_priorities(s, idx, td_mix)
        return _restack(s)

    draw_assemble = smap(
        _draw_assemble, mesh=mesh,
        in_specs=(state_spec, P(), P()),
        out_specs=(P(axis), batch_spec),
    )
    write_back = smap(
        _write_back, mesh=mesh,
        in_specs=(state_spec, P(axis), P(axis)),
        out_specs=state_spec,
    )

    def fused(train_state, replay_state, key, beta):
        k_sample, k_learn = jax.random.split(key)
        idx, batch = draw_assemble(replay_state, k_sample, beta)
        train_state, info = learn_step(train_state, batch, k_learn)
        replay_state = write_back(replay_state, idx, info["priorities"])
        return train_state, replay_state, info

    fused.draw_assemble = draw_assemble  # exposed for tests
    return fused
