"""replay/net: the cross-host replay plane (disaggregated Ape-X replay).

Shard servers (`ReplayShardServer`) own blocks of the global prioritized
replay and speak the netcore frame protocol; actors feed them through
`AppendClient` spoolers, the learner drains assembled batches through
`SampleClient` pipelines, and `RemoteReplayPlane` wires discovery + the
drop/readmit failure lifecycle into parallel/apex.py behind the
all-default-off ``replay_net_*`` config.

Exports resolve lazily (PEP 562): every module here is jax-free, but the
house rule keeps package ``__init__``s import-cheap regardless.
"""

from typing import TYPE_CHECKING

_EXPORTS = {
    "protocol": "rainbow_iqn_apex_tpu.replay.net",
    "client": "rainbow_iqn_apex_tpu.replay.net",
    "server": "rainbow_iqn_apex_tpu.replay.net",
    "plane": "rainbow_iqn_apex_tpu.replay.net",
    "shm": "rainbow_iqn_apex_tpu.replay.net",
    "ReplayNetError": "rainbow_iqn_apex_tpu.replay.net.protocol",
    "PeerDead": "rainbow_iqn_apex_tpu.replay.net.protocol",
    "ReplayShardServer": "rainbow_iqn_apex_tpu.replay.net.server",
    "ReplayPeer": "rainbow_iqn_apex_tpu.replay.net.client",
    "AppendClient": "rainbow_iqn_apex_tpu.replay.net.client",
    "SampleClient": "rainbow_iqn_apex_tpu.replay.net.client",
    "RemoteReplayPlane": "rainbow_iqn_apex_tpu.replay.net.plane",
}

__all__ = sorted(_EXPORTS)

_SUBMODULES = ("protocol", "client", "server", "plane", "shm")


def __getattr__(name: str):
    module = _EXPORTS.get(name)
    if module is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    if name in _SUBMODULES:
        return importlib.import_module(f"{module}.{name}")
    return getattr(importlib.import_module(module), name)


def __dir__():
    return __all__


if TYPE_CHECKING:  # static analyzers see the eager imports
    from rainbow_iqn_apex_tpu.replay.net import (  # noqa: F401
        client,
        plane,
        protocol,
        server,
        shm,
    )
    from rainbow_iqn_apex_tpu.replay.net.client import (  # noqa: F401
        AppendClient,
        ReplayPeer,
        SampleClient,
    )
    from rainbow_iqn_apex_tpu.replay.net.plane import (  # noqa: F401
        RemoteReplayPlane,
    )
    from rainbow_iqn_apex_tpu.replay.net.protocol import (  # noqa: F401
        PeerDead,
        ReplayNetError,
    )
    from rainbow_iqn_apex_tpu.replay.net.server import (  # noqa: F401
        ReplayShardServer,
    )
