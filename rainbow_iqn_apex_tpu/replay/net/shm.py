"""Same-host shared-memory fast path for the replay sample plane.

Why this exists: on a single host the socket wire path pays two kernel
copies per batch (user->kernel on send, kernel->user on receive) — ~0.55 ms
for a 1.8 MB Atari batch even over AF_UNIX, which alone busts the "within
2x of in-process" budget when learner and replay server are colocated (the
TPU-host deployment the paper's Ape-X topology implies).  The arena removes
both copies: the server writes each encoded batch ONCE into a shared
``memfd`` ring of slots, the socket carries only a tiny control frame
(metas + slot byte-offsets), and the client's decode returns numpy views
straight over its own mapping of the same physical pages.

Handshake (AF_UNIX connections only — fd passing needs SCM_RIGHTS):

1. The server listens on the abstract socket ``\\0rn-replay.<tcp_port>``
   beside its TCP port (Linux only; the name derives from the TCP port, so
   discovery stays the lease's job).
2. The client's FIRST bytes on that socket are a 16-byte preamble
   ``RNSHMRQ1 | flags u64`` (flag 1 = wants an arena; append-only clients
   leave it 0 and still get the faster AF_UNIX byte path).
3. The server replies ``RNSHMEM1 | arena_bytes u64``; when
   ``arena_bytes > 0`` the memfd rides the same sendmsg as ancillary
   SCM_RIGHTS data.  Both sides then speak normal netcore framing.

Slot protocol: sample replies carry ``slots: [byte_offset | null, ...]``
parallel to ``batches``; a null means that batch's bytes ride the frame
blob as usual (arena full, or the batch outgrew its slot).  The client
returns consumed offsets on later ``sample`` requests under ``free`` —
deferred by a small hold window (`SampleClient` ``shm_hold``) so the
learner's zero-copy views are never overwritten mid-read.  A connection's
death frees everything: the arena is per-connection and dies with it.

Integrity: arena bytes never traverse a network, so v2 column word-sums
are skipped for slot batches (the control frame itself stays CRC-checked);
blob-path batches keep their ``sum64`` stamps.  Everything is stdlib
(``os.memfd_create`` + ``mmap`` + ``socket.send_fds``) — no new deps.
"""

from __future__ import annotations

import mmap
import os
import socket
import struct
import sys
from typing import Any, List, Optional, Sequence, Tuple

MAGIC_REQ = b"RNSHMRQ1"
MAGIC_HELLO = b"RNSHMEM1"
_PRE = struct.Struct(">8sQ")
PREAMBLE_BYTES = _PRE.size  # both directions: 16 bytes exactly
FLAG_WANT_ARENA = 1

# slots are page-aligned; the margin absorbs meta jitter between batches
# (palette/raw fallbacks move a column by at most a few hundred bytes)
_SLOT_ALIGN = 4096

# hosts a client treats as "this machine" for the fast-path dial
LOCAL_HOSTS = frozenset({"127.0.0.1", "::1", "localhost"})


def available() -> bool:
    """True when this platform can run the fast path: abstract AF_UNIX
    names + memfd + SCM_RIGHTS helpers (Linux, Python >= 3.9)."""
    return (sys.platform.startswith("linux")
            and hasattr(os, "memfd_create")
            and hasattr(socket, "AF_UNIX")
            and hasattr(socket, "send_fds")
            and hasattr(socket, "recv_fds"))


def unix_path(port: int) -> str:
    """The abstract-namespace socket name derived from the TCP port (the
    port is host-unique, so the name is too — no filesystem cleanup)."""
    return f"\0rn-replay.{int(port)}"


def pack_request(want_arena: bool) -> bytes:
    return _PRE.pack(MAGIC_REQ, FLAG_WANT_ARENA if want_arena else 0)


def parse_request(data: bytes) -> Optional[int]:
    """flags, or None when the preamble is not ours (close the conn)."""
    magic, flags = _PRE.unpack(data[:PREAMBLE_BYTES])
    return int(flags) if magic == MAGIC_REQ else None


def pack_hello(arena_bytes: int) -> bytes:
    return _PRE.pack(MAGIC_HELLO, int(arena_bytes))


def parse_hello(data: bytes) -> Optional[int]:
    magic, nbytes = _PRE.unpack(data[:PREAMBLE_BYTES])
    return int(nbytes) if magic == MAGIC_HELLO else None


class ServerArena:
    """The server half: owns the memfd mapping and the slot free-list.

    Slot size is fixed lazily at the first batch write, from the batch's
    RAW byte bound (every v2 encoding is <= its raw form, so one bound
    covers palette/fallback jitter).  ``alloc``/``release`` are NOT
    self-locking — the shard server already serialises arena access under
    its own lock."""

    def __init__(self, mm: mmap.mmap, nbytes: int):
        self.mm = mm
        self.view = memoryview(mm)
        self.nbytes = int(nbytes)
        self.slot_bytes = 0  # unsized until the first write
        self.total_slots = 0
        self.free: List[int] = []  # byte offsets
        self._free_set = set()

    @classmethod
    def create(cls, nbytes: int) -> Tuple["ServerArena", int]:
        """(arena, fd) — the fd is for the SCM_RIGHTS handoff; close it
        after sending (the mapping keeps the memory alive)."""
        fd = os.memfd_create("rn-replay-arena")
        os.ftruncate(fd, int(nbytes))
        mm = mmap.mmap(fd, int(nbytes))
        return cls(mm, int(nbytes)), fd

    def ensure_sized(self, raw_bound: int) -> None:
        if self.slot_bytes:
            return
        slot = -(-int(raw_bound) // _SLOT_ALIGN) * _SLOT_ALIGN + _SLOT_ALIGN
        self.slot_bytes = slot
        self.total_slots = self.nbytes // slot
        self.free = [i * slot for i in range(self.total_slots - 1, -1, -1)]
        self._free_set = set(self.free)

    def alloc(self, needed: int) -> Optional[int]:
        """A slot's byte offset, or None (arena exhausted / batch too big
        for a slot — the caller falls back to the frame-blob path)."""
        if not self.free or needed > self.slot_bytes:
            return None
        off = self.free.pop()
        self._free_set.discard(off)
        return off

    def release(self, off: int) -> bool:
        """Return one offset to the free list; False (ignored) for
        anything a buggy or malicious client sends that we never lent."""
        off = int(off)
        if (self.slot_bytes <= 0 or off % self.slot_bytes
                or not 0 <= off < self.total_slots * self.slot_bytes
                or off in self._free_set):
            return False
        self.free.append(off)
        self._free_set.add(off)
        return True

    def write(self, off: int, buffers: Sequence[Any]) -> int:
        """Pack the batch's wire buffers contiguously at ``off`` (the ONE
        copy this path makes); returns the bytes written."""
        view = self.view
        pos = off
        for b in buffers:
            n = len(b) if isinstance(b, bytes) else b.nbytes
            if n:
                view[pos:pos + n] = b
                pos += n
        return pos - off

    def close(self) -> None:
        try:
            self.view.release()
            self.mm.close()
        except (BufferError, ValueError, OSError):
            pass  # exported views keep the pages alive; GC finishes it


class ClientArena:
    """The client half: a read-only view over the server's arena pages.

    Never explicitly closed — batches hand out zero-copy numpy views over
    this mapping, so the mapping simply drops out of scope on reconnect
    and is garbage-collected when the last view dies."""

    def __init__(self, mm: mmap.mmap, nbytes: int):
        self.mm = mm
        self.view = memoryview(mm).toreadonly()
        self.nbytes = int(nbytes)

    @classmethod
    def from_fd(cls, fd: int, nbytes: int) -> "ClientArena":
        try:
            return cls(mmap.mmap(fd, int(nbytes), prot=mmap.PROT_READ),
                       int(nbytes))
        finally:
            os.close(fd)
