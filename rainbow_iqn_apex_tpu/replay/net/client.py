"""Client half of the cross-host replay plane.

Three layers, mirroring the serving plane's client (serving/net/client.py):

- `ReplayPeer` — one TCP connection to one shard server, demultiplexed by a
  reader thread: requests are settled by rid, connection loss fails every
  in-flight request fast with `PeerDead`, re-dials ride the shared
  `RetryPolicy` backoff, and every reply's piggyback state (size/mass/
  epoch/shard range) is folded into cheap attributes the callers rank on.
- `AppendClient` — the actor side: ``append()`` never blocks the env loop
  (it spools the tick locally and returns), a worker thread coalesces
  spooled ticks into batched CRC-framed append blocks and ships them with
  bounded in-flight; a FULL spool sheds the newest tick with a reasoned,
  rate-limited row (backpressure never wedges acting — the serving plane's
  shed story, append edition).  Blocks refused by the server's epoch fence
  are DROPPED (a stale incarnation's spool must not resurrect priorities);
  blocks that died in flight re-spool and re-ship after reconnect, so an
  acked row is never lost and an unacked one is never silently dropped
  while the server lives.
- `SampleClient` — the learner side: pipelines ``depth`` sample requests
  over the wire (``sample_ahead_depth``), hands back assembled host batches
  + global indices, routes batched priority write-backs to the owning peer
  by global slot range, and exposes ``flush()`` for the `WritebackRing`
  drain boundary.  A dead peer's in-flight requests re-route to survivors
  (survivors-only sampling); ``drop_peer``/``readmit_peer`` are the wire
  twins of ``ShardedReplay.drop_shard``/``readmit_shard``.

jax-free: the actor spool runs in processes with no device runtime, and the
learner-side gathers are plain numpy under ``hostsync`` discipline
(analysis/hostsync_lint.py declares the hot path).
"""

from __future__ import annotations

import collections
import os
import socket
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from rainbow_iqn_apex_tpu.netcore import chaos, framing
from rainbow_iqn_apex_tpu.replay.buffer import SampledBatch
from rainbow_iqn_apex_tpu.replay.net import protocol, shm
from rainbow_iqn_apex_tpu.replay.net.protocol import PeerDead
from rainbow_iqn_apex_tpu.utils import hostsync
from rainbow_iqn_apex_tpu.utils.faults import RetryPolicy


class _Pending:
    """One in-flight request: settled by the reader thread with the reply
    (header, blob) or an error.  ``blob`` is a read-only memoryview over
    the reply frame's own receive buffer (`recv_frame_view`) — decode
    paths view it zero-copy; nothing retains it past decode."""

    __slots__ = ("event", "header", "blob", "error")

    def __init__(self):
        self.event = threading.Event()
        self.header: Optional[Dict[str, Any]] = None
        self.blob: Any = b""
        self.error: Optional[BaseException] = None


class ReplayPeer:
    """One connection to one replay shard server.

    The piggyback attributes (``size``/``sampleable``/``mass``/``epoch``/
    ``shard_base``/``shards``/``capacity``) refresh on every reply frame, so
    ranking and routing across N peers costs zero dedicated RPCs; ``epoch``
    is what append/update frames must stamp to pass the server's fence.
    """

    def __init__(self, host: str, port: int, peer_id: Optional[int] = None,
                 retry: Optional[RetryPolicy] = None,
                 probe_timeout_s: float = 0.5,
                 ack_timeout_s: float = 10.0,
                 max_frame_bytes: int = framing.DEFAULT_MAX_FRAME,
                 local_fastpath: bool = True,
                 logger=None, obs_registry=None, connect: bool = True):
        self.host = str(host)
        self.port = int(port)
        self.peer_id = peer_id
        self.peer = f"{self.host}:{self.port}"
        self.retry = retry if retry is not None else RetryPolicy(
            attempts=6, base_delay_s=0.2, max_delay_s=5.0)
        self.probe_timeout_s = float(probe_timeout_s)
        self.ack_timeout_s = float(ack_timeout_s)
        self.max_frame_bytes = int(max_frame_bytes)
        self.logger = logger
        self.obs_registry = obs_registry
        # piggyback state: unknown until the first reply teaches us
        self.size = 0
        self.sampleable = False
        self.mass = 0.0
        self.epoch: Optional[int] = None
        self.shard_base = 0
        self.shards = 0
        self.capacity = 0
        # negotiated batch wire codec: 1 until the server's piggyback
        # advertises better — an old server never sees v2 fields
        self.wire_codec = 1
        # same-host fast path (replay/net/shm.py): when the server is
        # colocated, the dial goes over AF_UNIX and sample batches arrive
        # in a shared-memory arena instead of through the socket.  The
        # arena is PER-CONNECTION — a reconnect drops it (and any offsets
        # queued for return) and negotiates a fresh one.
        self.local_fastpath = bool(local_fastpath)
        self.arena: Optional[shm.ClientArena] = None
        self._shm_free: List[int] = []  # consumed slots to return
        # counters (the plane's periodic `replay_net` stats row)
        self.bytes_sent = 0
        self.bytes_recv = 0
        self.reconnects = 0
        self.probe_timeouts = 0
        self.rtt_ms: Optional[float] = None
        self._lock = threading.Lock()  # socket lifecycle + pending map
        self._wlock = threading.Lock()  # serialises frame writes
        self._sock: Optional[socket.socket] = None
        self._gen = 0  # connection generation (reader threads self-retire)
        self._rid = 0
        self._pending: Dict[int, _Pending] = {}
        self._ever_connected = False
        self._closed = False
        # backoff state: the shared RetryPolicy schedule, clamped at its
        # ceiling — a dead server is retried forever at the ceiling;
        # eviction is the PLANE's call via the lease, not the socket's
        self._delays = list(self.retry.delays()) or [self.retry.base_delay_s]
        self._fail_streak = 0
        self._next_dial = 0.0
        if connect:
            self.connect()

    # ---------------------------------------------------------- connection
    def _log(self, event: str, **fields: Any) -> None:
        if self.logger is not None:
            try:
                self.logger.log("replay_net", event=event, peer=self.peer,
                                server=self.peer_id, **fields)
            except Exception:
                pass  # telemetry must never break the transport

    def _count(self, name: str, n: int = 1) -> None:
        if self.obs_registry is not None:
            self.obs_registry.counter(name, "replay_net").inc(n)

    def _dial_unix(self, timeout: float
                   ) -> Tuple[socket.socket, Optional[shm.ClientArena]]:
        """Dial the server's abstract AF_UNIX socket and run the shm
        preamble: request an arena, map the memfd the hello carries (via
        SCM_RIGHTS).  Raises OSError on ANY miss — the caller falls back
        to the TCP dial, which is always correct, just slower."""
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        fds: List[int] = []
        try:
            sock.settimeout(timeout)
            sock.connect(shm.unix_path(self.port))
            sock.sendall(shm.pack_request(True))
            buf = b""
            while len(buf) < shm.PREAMBLE_BYTES:
                data, newfds, _flags, _addr = socket.recv_fds(
                    sock, shm.PREAMBLE_BYTES - len(buf), 4)
                fds.extend(newfds)
                if not data:
                    raise OSError("peer closed during shm hello")
                buf += data
            nbytes = shm.parse_hello(buf)
            if nbytes is None:
                raise OSError("unrecognized shm hello")
            arena = None
            if nbytes > 0 and fds:
                arena = shm.ClientArena.from_fd(fds.pop(0), nbytes)
            sock.settimeout(None)
            return sock, arena
        except (OSError, ValueError):
            try:
                sock.close()
            except OSError:
                pass
            raise
        finally:
            for fd in fds:  # any extras a confused peer attached
                try:
                    os.close(fd)
                except OSError:
                    pass

    def connect(self, timeout_s: Optional[float] = None) -> bool:
        """One bounded dial attempt; True when a connection is live."""
        with self._lock:
            if self._closed:
                return False
            if self._sock is not None:
                return True
        timeout = (self.probe_timeout_s if timeout_s is None
                   else timeout_s)
        sock = arena = None
        if (self.local_fastpath and shm.available()
                and self.host in shm.LOCAL_HOSTS):
            try:
                sock, arena = self._dial_unix(timeout)
            except (OSError, ValueError):
                sock = arena = None  # no unix listener / old server: TCP
        try:
            if sock is None:
                sock = socket.create_connection((self.host, self.port),
                                                timeout=timeout)
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                sock.settimeout(None)  # reader blocks; writes are sendall
            sock = chaos.maybe_wrap(sock, peer=f"replay{self.peer_id}",
                                    logger=self.logger)
        except OSError:
            with self._lock:
                self._fail_streak += 1
                delay = self._delays[
                    min(self._fail_streak - 1, len(self._delays) - 1)]
                self._next_dial = time.monotonic() + delay
            return False
        with self._lock:
            if self._closed:
                sock.close()
                return False
            self._sock = sock
            self.arena = arena
            self._shm_free = []
            self._gen += 1
            gen = self._gen
            self._fail_streak = 0
            reconnected = self._ever_connected
            self._ever_connected = True
            if reconnected:
                self.reconnects += 1
        threading.Thread(
            target=self._read_loop, args=(sock, gen),
            name=f"replaynet-client-{self.peer}", daemon=True).start()
        self._log("reconnect" if reconnected else "connect")
        if reconnected:
            self._count("replaynet_reconnects_total")
        return True

    def _ensure_connected(self) -> bool:
        """Connected, or one dial attempt if the backoff schedule is due."""
        with self._lock:
            if self._sock is not None:
                return True
            if self._closed or time.monotonic() < self._next_dial:
                return False
        return self.connect()

    def connected(self) -> bool:
        with self._lock:
            return self._sock is not None

    def alive(self) -> bool:
        if self._closed:
            return False
        return self._ensure_connected()

    def _drop(self, sock: socket.socket, gen: int, why: str) -> None:
        """Tear the connection down once; fail every in-flight request."""
        with self._lock:
            if gen != self._gen or self._sock is not sock:
                return  # an older generation already replaced
            self._sock = None
            # the arena died with the connection server-side; outstanding
            # zero-copy views keep the client mapping alive until GC, and
            # queued frees are moot (the next conn gets a FRESH arena)
            self.arena = None
            self._shm_free = []
            pending, self._pending = self._pending, {}
            self._next_dial = time.monotonic()  # first re-dial is immediate
        try:
            sock.close()
        except OSError:
            pass
        err = PeerDead(f"connection to replay server {self.peer} "
                       f"lost ({why})")
        for p in pending.values():
            p.error = err
            p.event.set()
        if not self._closed:
            self._log("disconnect", why=why, inflight=len(pending))
            self._count("replaynet_disconnects_total")

    def close(self) -> None:
        with self._lock:
            self._closed = True
            sock, gen = self._sock, self._gen
        if sock is not None:
            self._drop(sock, gen, "closed")

    def kick(self, why: str = "request timeout") -> None:
        """Force-drop the CURRENT connection: fail every in-flight request
        now, re-dial lazily on the next request.  For callers that observed
        the link wedged — a request timed out while the lease stays fresh
        (one-way partition, hung server).  Without this, each sibling
        in-flight request on the wedged link serializes its own full wait
        budget (requests sent into a TX-dropping partition never get a
        reply), stalling the sampler for N x ack_timeout_s after the
        partition heals; the drop settles them all with ``PeerDead``
        immediately and also reclaims their pending slots."""
        with self._lock:
            sock, gen = self._sock, self._gen
        if sock is not None:
            self._drop(sock, gen, why)

    # ------------------------------------------------------------ shm slots
    def shm_release(self, off: int, arena: shm.ClientArena) -> None:
        """Queue one consumed arena offset for return to the server on the
        next sample request.  ``arena`` is the mapping the offset belongs
        to — a stale release (the connection reconnected underneath) is
        silently dropped rather than poisoning the NEW arena's free list."""
        with self._lock:
            if self.arena is arena:
                self._shm_free.append(off)

    def take_shm_frees(self) -> List[int]:
        with self._lock:
            if not self._shm_free:
                return []
            out, self._shm_free = self._shm_free, []
            return out

    # ---------------------------------------------------------- frame I/O
    def _send(self, sock: socket.socket, gen: int,
              header: Dict[str, Any], blob: Any = b"") -> None:
        """``blob`` is bytes or a LIST of buffers — the latter ships
        zero-copy through the vectored sendmsg path."""
        buffers = blob if isinstance(blob, list) else ([blob] if blob
                                                       else [])
        try:
            with self._wlock:
                self.bytes_sent += framing.send_frame_views(sock, header,
                                                            buffers)
        except (OSError, framing.FrameError) as e:
            self._drop(sock, gen, f"send failed: {e}")
            raise PeerDead(
                f"replay server {self.peer} unreachable mid-send: "
                f"{e}") from e

    def _read_loop(self, sock: socket.socket, gen: int) -> None:
        while True:
            try:
                # one allocation per frame; the blob memoryview hands
                # zero-copy array views to the batch decoder
                frame = framing.recv_frame_view(sock, self.max_frame_bytes)
            except (OSError, framing.FrameError) as e:
                self._drop(sock, gen, f"{type(e).__name__}: {e}")
                return
            if frame is None:
                self._drop(sock, gen, "peer closed")
                return
            header, blob = frame
            self.bytes_recv += (framing.PREFIX_BYTES + framing.TRAILER_BYTES
                                + len(blob) + 64)  # header ~estimated
            try:
                self._on_frame(header, blob)
            except Exception:
                pass  # one malformed-but-framed reply must not kill the link

    def _refresh(self, header: Dict[str, Any]) -> None:
        """Fold the state every server reply piggybacks."""
        if "size" in header:
            self.size = int(header["size"])
        if "sampleable" in header:
            self.sampleable = bool(header["sampleable"])
        if "mass" in header:
            self.mass = float(header["mass"])
        if "epoch" in header:
            self.epoch = int(header["epoch"])
        if "shard_base" in header:
            self.shard_base = int(header["shard_base"])
        if "shards" in header:
            self.shards = int(header["shards"])
        if "capacity" in header:
            self.capacity = int(header["capacity"])
        if "wire" in header:
            self.wire_codec = min(int(header["wire"]),
                                  protocol.WIRE_CODEC_MAX)

    def slot_range(self) -> Tuple[int, int]:
        """The GLOBAL slot-id interval this peer's shard block owns (for
        write-back routing).  (0, 0) until the first reply taught us."""
        lo = self.shard_base * self.capacity
        return lo, lo + self.shards * self.capacity

    def _on_frame(self, header: Dict[str, Any], blob: bytes) -> None:
        self._refresh(header)
        rid = header.get("rid")
        p = self._pending.pop(rid, None) if rid is not None else None
        if p is None:
            return
        if header.get("op") == "rerr":
            p.error = protocol.wire_error(header.get("etype", ""),
                                          header.get("msg", "server error"))
        else:
            p.header, p.blob = header, blob
        p.event.set()

    # ------------------------------------------------------------- requests
    def start_request(self, header: Dict[str, Any],
                      blob: Any = b"") -> _Pending:
        """Send one request; the returned pending settles with the reply (or
        `PeerDead` the moment the connection dies)."""
        if not self._ensure_connected():
            raise PeerDead(f"replay server {self.peer} unreachable")
        p = _Pending()
        with self._lock:
            if self._sock is None:
                raise PeerDead(f"no connection to replay server {self.peer}")
            sock, gen = self._sock, self._gen
            rid = self._rid = self._rid + 1
            self._pending[rid] = p
        self._send(sock, gen, {**header, "rid": rid}, blob)
        return p

    def wait(self, p: _Pending, timeout_s: Optional[float] = None
             ) -> Tuple[Dict[str, Any], bytes]:
        """Block until ``p`` settles; returns (header, blob) or raises the
        mapped wire error / TimeoutError."""
        budget = self.ack_timeout_s if timeout_s is None else timeout_s
        if not p.event.wait(budget):
            raise TimeoutError(
                f"replay server {self.peer} did not answer within "
                f"{budget}s (hung or dying)")
        if p.error is not None:
            raise p.error
        assert p.header is not None
        return p.header, p.blob

    def request(self, header: Dict[str, Any], blob: bytes = b"",
                timeout_s: Optional[float] = None
                ) -> Tuple[Dict[str, Any], bytes]:
        """One synchronous RPC."""
        return self.wait(self.start_request(header, blob), timeout_s)

    def probe(self, timeout_s: Optional[float] = None) -> Optional[float]:
        """Bounded liveness probe: ping -> rtt_ms, refreshing the cached
        piggyback state.  None on timeout or a dead link — never blocks
        past the bound."""
        budget = self.probe_timeout_s if timeout_s is None else timeout_s
        t0 = time.monotonic()
        try:
            self.request({"op": "ping"}, timeout_s=budget)
        except TimeoutError:
            # connected but not answering: a WEDGED server — distinct from
            # unreachable (whose disconnect row tells that story already)
            self.probe_timeouts += 1
            self._log("probe_timeout", budget_s=budget)
            self._count("replaynet_probe_timeouts_total")
            return None
        except PeerDead:
            return None
        self.rtt_ms = round((time.monotonic() - t0) * 1e3, 3)
        return self.rtt_ms

    def stats(self) -> Dict[str, Any]:
        return {"peer": self.peer, "server": self.peer_id,
                "connected": self.connected(), "rtt_ms": self.rtt_ms,
                "shm": self.arena is not None,
                "reconnects": self.reconnects,
                "probe_timeouts": self.probe_timeouts,
                "bytes_sent": self.bytes_sent,
                "bytes_recv": self.bytes_recv}

    @classmethod
    def from_lease(cls, lease, **kwargs: Any) -> "ReplayPeer":
        """Build from a ``replay_shard`` lease advertising addr:port
        (grown by ``ReplayShardServer.attach_lease``)."""
        if not lease.addr or not lease.port:
            raise ValueError(
                f"lease for host {lease.host} carries no addr:port "
                "(not serving replay over the net)")
        return cls(lease.addr, lease.port, peer_id=lease.host, **kwargs)


class AppendClient:
    """Actor-side spooler: ``append()`` is non-blocking (env loops never
    wait on the wire), a worker thread ships coalesced epoch-stamped append
    blocks with bounded in-flight, and a full spool sheds with a reasoned
    row instead of backpressuring the actor into a stall."""

    def __init__(self, peer: ReplayPeer, spool_ticks: int = 4096,
                 inflight: int = 4, coalesce: int = 4,
                 logger=None, obs_registry=None, own_peer: bool = True):
        self.peer = peer
        self.spool_ticks = max(int(spool_ticks), 1)
        self.inflight = max(int(inflight), 1)
        self.coalesce = max(int(coalesce), 1)
        self.logger = logger
        self.obs_registry = obs_registry
        self._own_peer = own_peer
        self._spool: "collections.deque" = collections.deque()
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._stop = threading.Event()
        # counters (the smoke's zero-loss bookkeeping + obs rows)
        self.spooled_ticks = 0
        self.acked_rows = 0
        self.fenced_rows = 0
        self.shed_ticks = 0
        self._inflight = 0  # blocks shipped, ack outstanding (worker-owned)
        self._last_shed_log = 0.0
        self._thread = threading.Thread(
            target=self._run, name=f"replaynet-append-{peer.peer}",
            daemon=True)
        self._thread.start()

    # ------------------------------------------------------------ producer
    def append(self, frames: np.ndarray, actions: np.ndarray,
               rewards: np.ndarray, terminals: np.ndarray,
               priorities: Optional[np.ndarray] = None,
               truncations: Optional[np.ndarray] = None) -> bool:
        """Spool one lockstep lane tick (the `ShardedReplay.append_batch`
        row shape).  Returns False — and sheds the tick with a rate-limited
        reasoned row — when the spool is full (server dead or slow past the
        spool's buffering horizon); the actor keeps acting either way."""
        with self._lock:
            if len(self._spool) >= self.spool_ticks:
                self.shed_ticks += 1
                shed = self.shed_ticks
            else:
                # copy: callers reuse their staging buffers per tick
                self._spool.append((
                    np.array(frames, copy=True), np.array(actions, copy=True),
                    np.array(rewards, copy=True),
                    np.array(terminals, copy=True),
                    None if priorities is None else np.array(priorities,
                                                             copy=True),
                    None if truncations is None else np.array(truncations,
                                                              copy=True)))
                self.spooled_ticks += 1
                shed = None
        if shed is None:
            self._wake.set()
            return True
        if self.obs_registry is not None:
            self.obs_registry.counter(
                "replaynet_shed_ticks_total", "replay_net").inc()
        now = time.monotonic()
        if now - self._last_shed_log > 5.0 and self.logger is not None:
            self._last_shed_log = now
            try:
                self.logger.log(
                    "replay_net", event="spool_shed", peer=self.peer.peer,
                    shed_ticks=shed, spool=self.spool_ticks,
                    why="spool full: server unreachable or appends "
                        "outpacing the wire; newest tick dropped so the "
                        "actor keeps acting")
            except Exception:
                pass
        return False

    def spool_depth(self) -> int:
        with self._lock:
            return len(self._spool)

    # ------------------------------------------------------------- shipper
    def _take_block(self) -> Optional[List[tuple]]:
        """Pop up to ``coalesce`` ticks sharing one optional-column
        signature (priorities/truncations present-or-not must be uniform
        inside a block)."""
        with self._lock:
            if not self._spool:
                return None
            block = [self._spool.popleft()]
            sig = (block[0][4] is not None, block[0][5] is not None)
            while (self._spool and len(block) < self.coalesce
                   and (self._spool[0][4] is not None,
                        self._spool[0][5] is not None) == sig):
                block.append(self._spool.popleft())
        return block

    def _respool(self, block: List[tuple]) -> None:
        """Put an unacked block back at the FRONT (ship-after-reconnect:
        ring order inside the spool is preserved)."""
        with self._lock:
            for tick in reversed(block):
                self._spool.appendleft(tick)

    def _encode_block(self, block: List[tuple]
                      ) -> Tuple[Dict[str, Any], List[Any]]:
        arrays = {
            "frames": np.stack([t[0] for t in block]),
            "actions": np.stack([t[1] for t in block]),
            "rewards": np.stack([t[2] for t in block]),
            "terminals": np.stack([t[3] for t in block]),
        }
        if block[0][4] is not None:
            arrays["priorities"] = np.stack([t[4] for t in block])
        if block[0][5] is not None:
            arrays["truncations"] = np.stack([t[5] for t in block])
        # views over the freshly stacked arrays: start_request sends
        # synchronously, so their lifetime outlives the write
        metas, blob = protocol.encode_arrays_views(arrays)
        header: Dict[str, Any] = {"op": "append", "ticks": len(block),
                                  "arrays": metas}
        if self.peer.epoch is not None:
            # stamp the incarnation we believe owns the shard block; a
            # respawned server fences this and the ack's piggyback teaches
            # us the new epoch (the block is DROPPED by design — stale
            # spool contents must not land on the revived incarnation)
            header["epoch"] = self.peer.epoch
        return header, blob

    def _run(self) -> None:
        # (_Pending, rows, block) — the block travels with its ack so a
        # connection death can re-spool everything still unacked
        pending: List[Tuple[Any, int, List[tuple]]] = []
        while True:
            # settle the oldest in-flight ack once the window is full, the
            # spool is empty, or we are draining: bounded in-flight IS the
            # backpressure
            while pending and (len(pending) >= self.inflight
                               or self._stop.is_set()
                               or not self.spool_depth()):
                p, rows, block = pending[0]
                try:
                    header, _ = self.peer.wait(p)
                except (PeerDead, protocol.ReplayNetError, TimeoutError):
                    # connection died with blocks in flight: re-spool ALL of
                    # them, order preserved, and re-ship after reconnect.
                    # At-least-once: an ack lost AFTER the server applied
                    # the block re-ships as a duplicate tick (a replay ring
                    # absorbs that); an acked row is never lost.
                    for _p, _r, b in reversed(pending):
                        self._respool(b)
                    pending.clear()
                    self._inflight = 0
                    time.sleep(0.05)
                    break
                pending.pop(0)
                self._inflight = len(pending)
                if header.get("ok"):
                    self.acked_rows += int(header.get("rows", rows))
                elif header.get("fenced"):
                    # refused by the epoch fence: the rows are DROPPED by
                    # design (stale spool must not resurrect priorities on
                    # the revived incarnation) — the piggyback already
                    # refreshed peer.epoch, so the NEXT block ships live
                    self.fenced_rows += rows
                if not pending and not self.spool_depth():
                    break
            block = self._take_block()
            if block is None:
                if self._stop.is_set() and not pending:
                    return
                self._wake.wait(0.05)
                self._wake.clear()
                continue
            rows = sum(int(t[1].shape[0]) for t in block)
            header, blob = self._encode_block(block)
            try:
                pending.append(
                    (self.peer.start_request(header, blob), rows, block))
                self._inflight = len(pending)
            except PeerDead:
                # unreachable: re-spool and let the peer's backoff schedule
                # pace the retries (shed, if it comes, happens at append())
                self._respool(block)
                time.sleep(0.05)

    def flush(self, timeout_s: float = 30.0) -> bool:
        """Wait for the spool AND the in-flight window to drain (smoke /
        shutdown determinism).  True when fully drained in time."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                empty = not self._spool
            if empty and self._inflight == 0:
                return True
            time.sleep(0.02)
        return False

    def stats(self) -> Dict[str, Any]:
        return {"spooled_ticks": self.spooled_ticks,
                "acked_rows": self.acked_rows,
                "fenced_rows": self.fenced_rows,
                "shed_ticks": self.shed_ticks,
                "spool_depth": self.spool_depth(),
                **self.peer.stats()}

    def close(self) -> None:
        self._stop.set()
        self._wake.set()
        self._thread.join(timeout=10)
        if self._own_peer:
            self.peer.close()


class SampleClient:
    """Learner-side sampler: keeps ``depth`` sample requests in flight
    across the alive peers (each peer drawn ∝ its advertised priority mass
    — the proportional split `ShardedReplay.sample` computes in-process,
    here at server granularity), decodes replies into host `SampledBatch`es
    (GLOBAL indices), and routes priority write-backs to the owning peer."""

    def __init__(self, peers: Dict[int, ReplayPeer], batch_size: int,
                 beta_fn: Callable[[], float], depth: int = 2,
                 wb_inflight: int = 4, seed: int = 0,
                 depth_min: int = 1, depth_max: int = 8,
                 sample_many: int = 4, shm_hold: int = 2,
                 logger=None, obs_registry=None):
        self.peers = dict(peers)
        self.batch_size = int(batch_size)
        self.beta_fn = beta_fn
        # adaptive pipeline: ``depth`` (in BATCHES, in-flight + decoded
        # unconsumed) starts at the configured value and then tracks
        # measured RTT vs the consumer's drain interval — roughly
        # ceil(rtt/gap)+1 batches keep the learner fed without parking
        # depth_max batches of staleness when the link is fast
        self.depth_min = max(int(depth_min), 1)
        self.depth_max = max(int(depth_max), self.depth_min)
        self.depth = min(max(int(depth), self.depth_min), self.depth_max)
        # batches per sample RPC once the peer negotiates codec v2
        # ("sample_many"): amortizes header + syscall + queue-wait costs
        self.sample_many = max(int(sample_many), 1)
        # shm slot hold window: a zero-copy arena batch's slot is returned
        # to the server ``shm_hold`` get() calls AFTER the learner took it
        # — by then the learner's device transfer is long done, so the
        # server can never overwrite pages a live view still reads
        self.shm_hold = max(int(shm_hold), 1)
        self._hold: "collections.deque" = collections.deque()
        self.wb_inflight = max(int(wb_inflight), 1)
        self.logger = logger
        self.obs_registry = obs_registry
        self.rng = np.random.default_rng(seed)
        # failover: when set, update frames carry the learner's role epoch
        # so shard servers can refuse a superseded (zombie) learner's
        # write-backs.  None (default) leaves the wire format untouched.
        self.learner_epoch: Optional[int] = None
        self._dead: set = set()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._ready: "collections.deque" = collections.deque()
        self._ready_sem = threading.Semaphore(0)
        # permits count BATCHES; sized at the adaptive CEILING — the live
        # bound is self.depth, enforced by the top-up loop, the semaphore
        # is the hard backstop get() releases into
        self._space = threading.Semaphore(self.depth_max)
        # EWMAs feeding the adaptive depth (under _lock: written by the
        # run thread / the learner's get(), read by both + stats)
        self._rtt_s: Optional[float] = None
        self._gap_s: Optional[float] = None
        self._last_get: Optional[float] = None
        self._probe_unknown_at = 0.0  # next not-yet-sampleable peer probe
        # write-back channel state (learner thread only)
        self._wb_pending: List[Tuple[ReplayPeer, _Pending]] = []
        # counters
        self.batches_received = 0
        self.rows_sampled = 0
        self.updates_sent = 0
        self.updates_dropped = 0
        self.rerouted = 0
        self._thread = threading.Thread(
            target=self._run, name="replaynet-sample", daemon=True)
        self._thread.start()

    # ---------------------------------------------------------- peer set
    def _alive_peers(self) -> List[ReplayPeer]:
        with self._lock:
            return [p for pid, p in self.peers.items()
                    if pid not in self._dead]

    def drop_peer(self, pid: int) -> None:
        """Stop sampling from / writing back to ``pid`` (its server's lease
        expired).  The wire twin of ``ShardedReplay.drop_shard`` — but
        dropping the LAST peer is allowed here: the learner then blocks in
        ``get()`` until a peer readmits, which the smoke's never-stall gate
        bounds."""
        with self._lock:
            self._dead.add(pid)

    def readmit_peer(self, pid: int, peer: ReplayPeer) -> None:
        """Re-register a revived server (possibly at a new addr:port and
        ALWAYS at a fresh epoch — the fence the old incarnation's clients
        trip).  The wire twin of ``readmit_shard``."""
        with self._lock:
            old = self.peers.get(pid)
            self.peers[pid] = peer
            self._dead.discard(pid)
            self._probe_unknown_at = 0.0  # learn its piggyback on next pick
        if old is not None and old is not peer:
            old.close()

    def dead_peers(self) -> Tuple[int, ...]:
        with self._lock:
            return tuple(sorted(self._dead))

    # ------------------------------------------------------------- sampling
    def _pick_peer(self) -> Optional[ReplayPeer]:
        """Weighted draw ∝ advertised priority mass over the alive,
        sampleable peers — server-granular proportional sampling."""
        now = time.monotonic()
        with self._lock:
            probe_due = now >= self._probe_unknown_at
            if probe_due:
                self._probe_unknown_at = now + 1.0
        if probe_due:
            # peers whose piggyback is unknown (fresh readmit, still
            # warming) would otherwise NEVER be drawn while a sampleable
            # survivor exists — refresh them on a rate-limited bounded
            # probe so a revived server rejoins the draw
            for p in self._alive_peers():
                if not p.sampleable and p.alive():
                    p.probe()
        peers = [p for p in self._alive_peers()
                 if p.sampleable and p.connected()]
        if not peers:
            # nobody sampleable yet: probe one alive peer to refresh its
            # piggyback (bounded), covering warmup and post-readmit
            for p in self._alive_peers():
                if p.alive():
                    p.probe()
            return None
        masses = np.asarray([max(p.mass, 0.0) for p in peers], np.float64)
        if masses.sum() <= 0:
            return peers[int(self.rng.integers(len(peers)))]
        return peers[int(self.rng.choice(len(peers),
                                         p=masses / masses.sum()))]

    def _update_depth(self) -> None:
        """Re-target the pipeline depth from the RTT and consumption-gap
        EWMAs: just enough batches in flight to cover one round trip plus
        one being consumed, clamped to [depth_min, depth_max]."""
        with self._lock:
            rtt, gap = self._rtt_s, self._gap_s
            if rtt is None or gap is None:
                return
            want = int(np.ceil(rtt / max(gap, 1e-4))) + 1
            self.depth = min(max(want, self.depth_min), self.depth_max)

    def _run(self) -> None:
        # (peer, pending, batches requested, send stamp)
        inflight: List[Tuple[ReplayPeer, _Pending, int, float]] = []
        while not self._stop.is_set():
            # top up the pipeline to the (adaptive) depth in BATCHES; each
            # batch holds one _space permit so decoded-but-unconsumed
            # batches bound the window too
            while sum(e[2] for e in inflight) < self.depth:
                peer = self._pick_peer()
                if peer is None:
                    time.sleep(0.05)
                    break
                want = self.depth - sum(e[2] for e in inflight)
                n = (min(self.sample_many, max(want, 1))
                     if peer.wire_codec >= 2 else 1)
                got = 0
                while got < n and self._space.acquire(blocking=False):
                    got += 1
                if got == 0:
                    break  # window full of unconsumed batches
                req: Dict[str, Any] = {"op": "sample",
                                       "batch": self.batch_size,
                                       "beta": float(self.beta_fn())}
                if peer.wire_codec >= 2:
                    # negotiated: the server pre-assembles `got` batches
                    # into ONE compact-codec reply (sample_many)
                    req["codec"] = 2
                    req["n"] = got
                freed = peer.take_shm_frees()
                if freed:
                    # consumed arena slots ride back on the request the
                    # peer was getting anyway (shm.py's deferred-free leg)
                    req["free"] = freed
                try:
                    p = peer.start_request(req)
                except PeerDead:
                    for _ in range(got):
                        self._space.release()
                    continue
                inflight.append((peer, p, got, time.monotonic()))
            if not inflight:
                time.sleep(0.01)
                continue
            peer, p, n, t0 = inflight.pop(0)
            try:
                header, blob = peer.wait(p)
            except (protocol.ReplayNetError, ValueError, TimeoutError) as e:
                # dead peer / empty server / wedge: release the slots and
                # re-route the next request to the survivors
                self.rerouted += 1
                for _ in range(n):
                    self._space.release()
                if isinstance(e, TimeoutError):
                    # a TIMED-OUT request means the link is wedged (one-way
                    # partition, hung server) — typed errors settle fast,
                    # only silence burns the budget.  Drop the connection so
                    # sibling in-flight requests fail NOW instead of each
                    # serializing its own full wait budget, and the next
                    # request re-dials a fresh socket.
                    peer.kick()
                continue
            rtt = time.monotonic() - t0
            with self._lock:
                self._rtt_s = (rtt if self._rtt_s is None
                               else 0.8 * self._rtt_s + 0.2 * rtt)
            try:
                batches = self._decode_reply(peer, header, blob)
            except framing.FrameError:
                for _ in range(n):
                    self._space.release()
                continue
            # a still-warming server may answer with fewer batches than
            # asked — hand their permits back
            for _ in range(max(n - len(batches), 0)):
                self._space.release()
            with self._lock:
                self._ready.extend(batches)
            for _ in range(len(batches)):
                self._ready_sem.release()
            self._update_depth()
        # drain: settle nothing further, slots die with the thread

    def _decode_reply(self, peer: ReplayPeer, header: Dict[str, Any],
                      blob: Any) -> List[Tuple[SampledBatch, Any]]:
        """Decode one batch reply — v1 single batch, v2 sample_many, or
        the shm form (batches in the peer's arena, the blob only carrying
        any that fell back).  Returns ``(batch, hold)`` tuples: hold is
        None for socket batches, else the ``(peer, arena, slot_off)``
        ``get()`` must eventually hand to ``peer.shm_release``.
        LEAN: columns stay read-only views over the reply frame's buffer
        — or the arena mapping — (device staging only reads them); the ONE
        retained column, ``idx`` (held by `WritebackRing` across its whole
        ring depth), is decoded to an owned array so a pending write-back
        never pins a multi-MB frame blob.  v2's transformed columns (u32
        idx, fp16 weight/prob, palette discounts) decode owned by
        construction."""
        with hostsync.sanctioned():  # wire gather: the frontier's contract
            slot_of: List[Any] = []
            arena = peer.arena
            if int(header.get("codec", 1)) >= 2:
                metas_list = header.get("batches", ())
                slots = header.get("slots")
                if slots and arena is not None:
                    raws = []
                    off = 0  # walk of the blob's fallback batches
                    for metas, slot in zip(metas_list, slots):
                        if slot is None:
                            raws.append(protocol.decode_batch_v2(
                                metas, blob, off))
                            off += sum(int(m["nbytes"]) for m in metas)
                            slot_of.append(None)
                        else:
                            # zero-copy: columns view the shared mapping
                            raws.append(protocol.decode_batch_v2(
                                metas, arena.view, int(slot)))
                            slot_of.append(int(slot))
                else:
                    raws = protocol.decode_batches_v2(metas_list, blob)
            else:
                raws = [protocol.decode_arrays(header.get("arrays", ()),
                                               blob)]
            slot_of.extend([None] * (len(raws) - len(slot_of)))
            out: List[Tuple[SampledBatch, Any]] = []
            for arrays, slot in zip(raws, slot_of):
                idx = np.asarray(arrays["idx"], np.int64)
                if not idx.flags.owndata:
                    idx = idx.copy()  # v1 view -> owned (see above)
                batch = SampledBatch(
                    idx=idx,
                    obs=np.asarray(arrays["obs"]),
                    action=np.asarray(arrays["action"]),
                    reward=np.asarray(arrays["reward"]),
                    next_obs=np.asarray(arrays["next_obs"]),
                    discount=np.asarray(arrays["discount"]),
                    weight=np.asarray(arrays["weight"], np.float32),
                    prob=(np.asarray(arrays["prob"])
                          if "prob" in arrays else None))
                self.batches_received += 1
                self.rows_sampled += int(batch.idx.shape[0])
                out.append((batch, None if slot is None
                            else (peer, arena, slot)))
        return out

    def get(self, timeout: float = 60.0) -> SampledBatch:
        """Next pipelined batch (host arrays, GLOBAL indices).  Raises
        TimeoutError with a reasoned message when nothing arrives — the
        learner's stall alarm, same contract as `BatchPrefetcher.get`."""
        if not self._ready_sem.acquire(timeout=timeout):
            raise TimeoutError(
                f"no replay batch arrived for {timeout}s (all shard "
                "servers dead, empty, or unreachable — see the "
                "`replaynet:` section of obs_report)")
        now = time.monotonic()
        with self._lock:
            batch, hold = self._ready.popleft()
            # consumption-gap EWMA: the drain rate the adaptive depth
            # paces against
            if self._last_get is not None:
                gap = now - self._last_get
                self._gap_s = (gap if self._gap_s is None
                               else 0.8 * self._gap_s + 0.2 * gap)
            self._last_get = now
            # shm: park this batch's arena slot in the hold window; slots
            # older than ``shm_hold`` gets are queued for return (their
            # views are long consumed by the time the server reuses them)
            released = []
            if hold is not None:
                self._hold.append(hold)
            while len(self._hold) > self.shm_hold:
                released.append(self._hold.popleft())
        for peer, arena, off in released:
            peer.shm_release(off, arena)
        self._space.release()
        return batch

    def sampleable(self) -> bool:
        return any(p.sampleable for p in self._alive_peers())

    def size(self) -> int:
        return sum(p.size for p in self._alive_peers())

    # ------------------------------------------------------------ writeback
    def update_priorities(self, idx: np.ndarray, td_abs: np.ndarray) -> None:
        """Batched priority write-back, routed to the peer owning each
        global slot.  Fire-and-forget with bounded in-flight; rows owned by
        a dead peer are dropped (exactly the in-process dead-shard drop).
        Learner-thread only (the `WritebackRing` commit path)."""
        with hostsync.sanctioned():  # host routing math on the hot path
            idx = np.asarray(idx, np.int64).ravel()
            td = np.asarray(td_abs, np.float64).ravel()
            routed = np.zeros(idx.shape[0], bool)
            for peer in self._alive_peers():
                lo, hi = peer.slot_range()
                if hi <= lo:
                    continue
                m = (idx >= lo) & (idx < hi)
                if not m.any():
                    continue
                routed |= m
                metas, blob = protocol.encode_arrays_views(
                    {"idx": idx[m], "td": td[m]})
                header: Dict[str, Any] = {"op": "update", "arrays": metas}
                if peer.epoch is not None:
                    header["epoch"] = peer.epoch
                if self.learner_epoch is not None:
                    header["learner_epoch"] = self.learner_epoch
                while len(self._wb_pending) >= self.wb_inflight:
                    self._settle_one_wb()
                try:
                    self._wb_pending.append(
                        (peer, peer.start_request(header, blob)))
                    self.updates_sent += int(m.sum())
                except PeerDead:
                    self.updates_dropped += int(m.sum())
            dropped = int((~routed).sum())
        if dropped:
            self.updates_dropped += dropped

    def _settle_one_wb(self) -> None:
        peer, p = self._wb_pending.pop(0)
        try:
            peer.wait(p)
        except (protocol.ReplayNetError, ValueError, TimeoutError):
            pass  # priorities are advisory; the drop is already counted

    def flush(self, timeout_s: float = 10.0) -> None:
        """Settle every outstanding write-back ack (the `WritebackRing`
        drain boundary — ``on_drain`` lands here so a checkpoint's replay
        snapshot sees every priority the learner already computed)."""
        deadline = time.monotonic() + timeout_s
        while self._wb_pending and time.monotonic() < deadline:
            self._settle_one_wb()

    # ---------------------------------------------------------------- stats
    def stats(self) -> Dict[str, Any]:
        with self._lock:
            rtt, gap = self._rtt_s, self._gap_s
            depth = self.depth
        return {"batches_received": self.batches_received,
                "rows_sampled": self.rows_sampled,
                "updates_sent": self.updates_sent,
                "updates_dropped": self.updates_dropped,
                "rerouted": self.rerouted,
                "depth": depth,
                "sample_many": self.sample_many,
                "shm_peers": sum(1 for p in self._alive_peers()
                                 if p.arena is not None),
                "sample_rtt_ms": None if rtt is None else round(rtt * 1e3,
                                                                3),
                "consume_gap_ms": None if gap is None else round(gap * 1e3,
                                                                 3),
                "dead_peers": list(self.dead_peers()),
                "peers": [p.stats() for p in self._alive_peers()]}

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=10)
        self.flush(timeout_s=2.0)
        with self._lock:
            peers = list(self.peers.values())
            self.peers.clear()
        for p in peers:
            p.close()
