"""`RemoteReplayPlane` — the learner-side aggregate of the cross-host
replay plane: discovery, failure lifecycle, and the drop-in surfaces
`parallel/apex.py` swaps in when ``replay_net_remote`` is on.

Discovery reuses the elastic substrate wholesale: shard servers register
``replay_shard`` leases carrying ``addr:port`` + shard range + epoch
(`ReplayShardServer.attach_lease`), and the plane watches the SAME
heartbeat directory every other role already heals through — no second
discovery protocol.  The plane owns its own `HeartbeatMonitor` (edge state
is per-instance, so it cannot race the apex loop's fault-row monitor).

Failure lifecycle, mapped onto the in-process names:

    lease expires  -> drop_peer      (survivors-only sampling; the learner
                                      never stalls while ANY peer samples)
    lease revives  -> readmit_peer   (reconnect at the lease's addr:port;
                                      epoch-fenced — an OLDER epoch than
                                      the one last seen is a stale lease
                                      file, ignored, and the revived
                                      incarnation's fresh epoch is what
                                      append/update frames must stamp)

Snapshots run SERVER-side (``request_snapshot`` at the learner's
checkpoint step — the fence); the learner's own checkpoint carries no
replay payload when the plane is on.

jax-free: the plane is wiring and numpy routing.  The one device-touching
hop — staging a decoded host batch onto the accelerator — is an injected
callable (`make_prefetcher`'s ``to_device``), so apex keeps the jax half.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from rainbow_iqn_apex_tpu.parallel.elastic import (
    HeartbeatMonitor,
    heartbeat_dir,
)
from rainbow_iqn_apex_tpu.replay.net.client import (
    AppendClient,
    ReplayPeer,
    SampleClient,
)

_ROLE = "replay_shard"


class RemoteReplayPlane:
    """Aggregate client over every discovered replay shard server."""

    def __init__(self, cfg, lanes_total: int, metrics=None,
                 obs_registry=None):
        self.cfg = cfg
        self.lanes_total = int(lanes_total)
        self.metrics = metrics
        self.obs_registry = obs_registry
        self.total_shards = max(int(cfg.replay_shards), 1)
        if self.lanes_total % self.total_shards:
            raise ValueError(
                f"{self.lanes_total} lanes do not divide into "
                f"{self.total_shards} global shards (lane->shard pinning "
                "must be block-even, the ShardedReplay contract)")
        self.lanes_per_shard = self.lanes_total // self.total_shards
        timeout_s = float(getattr(cfg, "heartbeat_timeout_s", 0) or 10.0)
        self.monitor = HeartbeatMonitor(
            heartbeat_dir(cfg), timeout_s, self_id=cfg.process_id,
            skew_tolerance_s=getattr(cfg, "lease_skew_tolerance_s", 0.0))
        self.peers: Dict[int, ReplayPeer] = {}
        self._peer_epoch: Dict[int, int] = {}  # last epoch seen per server
        self.sampler: Optional[SampleClient] = None
        self._appenders: Dict[int, AppendClient] = {}
        self._append_active = False
        # failover: the learner's role epoch, stamped into update + snapshot
        # frames so shard servers latch the highest seen and refuse a
        # superseded (zombie) learner.  None = unstamped, the pre-failover
        # wire format byte for byte.
        self.learner_epoch: Optional[int] = None
        self.shed_lanes = 0  # append rows shed for lack of an alive owner
        self._last_stats = time.monotonic()
        self.discover()

    @classmethod
    def from_config(cls, cfg, lanes_total: int, metrics=None,
                    obs_registry=None) -> Optional["RemoteReplayPlane"]:
        """The config seam: ``replay_net_remote`` off (default) returns
        None — replay stays in-process, bitwise the pre-net path."""
        if not getattr(cfg, "replay_net_remote", False):
            return None
        return cls(cfg, lanes_total, metrics=metrics,
                   obs_registry=obs_registry)

    # ------------------------------------------------------------- discovery
    def _log(self, event: str, **fields: Any) -> None:
        if self.metrics is not None:
            try:
                self.metrics.log("replay_net", event=event, **fields)
            except Exception:
                pass

    def _new_peer(self, lease) -> ReplayPeer:
        cfg = self.cfg
        return ReplayPeer(
            lease.addr, lease.port, peer_id=lease.host,
            probe_timeout_s=float(
                getattr(cfg, "replay_net_probe_timeout_s", 0.5)),
            max_frame_bytes=int(
                getattr(cfg, "replay_net_max_frame_mb", 64)) << 20,
            local_fastpath=bool(
                getattr(cfg, "replay_net_local_fastpath", True)),
            logger=self.metrics, obs_registry=self.obs_registry)

    def discover(self) -> int:
        """Scan the lease directory for replay shard servers not yet in the
        peer set (startup + late registrants).  Returns the peer count."""
        for pid, lease in self.monitor.leases().items():
            if (lease.role != _ROLE or not lease.addr or not lease.port
                    or pid in self.peers):
                continue
            peer = self._new_peer(lease)
            self.peers[pid] = peer
            self._peer_epoch[pid] = int(lease.epoch)
            if self.sampler is not None:
                self.sampler.readmit_peer(pid, peer)
            if self._append_active:
                self._appenders[pid] = self._make_appender(peer)
            self._log("peer_discovered", server=pid,
                      peer=f"{lease.addr}:{lease.port}", epoch=lease.epoch)
        return len(self.peers)

    # ---------------------------------------------------------- append path
    def _make_appender(self, peer: ReplayPeer) -> AppendClient:
        cfg = self.cfg
        return AppendClient(
            peer, spool_ticks=int(getattr(cfg, "replay_net_spool", 4096)),
            inflight=int(getattr(cfg, "replay_net_inflight", 4)),
            logger=self.metrics, obs_registry=self.obs_registry,
            own_peer=False)  # peers are plane-owned (shared with sampling)

    def append_batch(self, frames: np.ndarray, actions: np.ndarray,
                     rewards: np.ndarray, terminals: np.ndarray,
                     priorities: Optional[np.ndarray] = None,
                     truncations: Optional[np.ndarray] = None) -> None:
        """Lockstep lane append, block-partitioned across the peers by
        their advertised shard ranges (exactly `ShardedReplay.append_batch`
        with servers in place of shards).  Lanes owned by a dead or
        undiscovered server are shed with a counter — their actor host's
        experience waits for readmission, survivors keep absorbing."""
        if not self._append_active:
            self._append_active = True
            for pid, peer in self.peers.items():
                if pid not in self._appenders:
                    self._appenders[pid] = self._make_appender(peer)
        lps = self.lanes_per_shard
        covered = 0
        for pid, ac in self._appenders.items():
            if self.sampler is not None and pid in self.sampler.dead_peers():
                continue
            peer = ac.peer
            if peer.shards <= 0:
                # piggyback not learned yet (no reply seen): one bounded
                # probe teaches the shard range; still unknown -> shed
                peer.probe()
                if peer.shards <= 0:
                    continue
            sl = slice(peer.shard_base * lps,
                       (peer.shard_base + peer.shards) * lps)
            ac.append(frames[sl], actions[sl], rewards[sl], terminals[sl],
                      None if priorities is None else priorities[sl],
                      None if truncations is None else truncations[sl])
            covered += peer.shards * lps
        if covered < self.lanes_total:
            self.shed_lanes += self.lanes_total - covered

    # ---------------------------------------------------------- sample path
    def start_sampling(self, batch_size: int,
                       beta_fn: Callable[[], float]) -> SampleClient:
        cfg = self.cfg
        self.sampler = SampleClient(
            self.peers, batch_size, beta_fn,
            depth=max(int(getattr(cfg, "sample_ahead_depth", 2)), 1),
            wb_inflight=max(int(getattr(cfg, "writeback_depth", 2)), 1),
            seed=int(getattr(cfg, "seed", 0)),
            depth_min=int(getattr(cfg, "replay_net_depth_min", 1)),
            depth_max=int(getattr(cfg, "replay_net_depth_max", 8)),
            sample_many=int(getattr(cfg, "replay_net_sample_many", 4)),
            logger=self.metrics, obs_registry=self.obs_registry)
        if self.learner_epoch is not None:
            self.sampler.learner_epoch = self.learner_epoch
        return self.sampler

    def set_learner_epoch(self, epoch: int) -> None:
        """Arm the failover epoch stamp: every subsequent priority
        write-back and snapshot request carries ``learner_epoch`` so the
        shard servers' latch can refuse frames from a learner this one
        superseded (and, symmetrically, refuse THIS learner once a
        successor claims a higher epoch)."""
        self.learner_epoch = int(epoch)
        if self.sampler is not None:
            self.sampler.learner_epoch = self.learner_epoch

    def make_prefetcher(self, batch_size: int, beta_fn: Callable[[], float],
                        to_device: Callable[[Any], Any], registry=None):
        """The apex learn-loop seam: a `BatchPrefetcher` whose sampler is
        the wire pipeline — ``get()`` yields ``(global_idx, device_batch)``
        exactly like the in-process `make_replay_prefetcher`.  ``to_device``
        is injected (agents.agent.to_device_batch) so this module stays
        jax-free; the import below is function-local for the same reason."""
        from rainbow_iqn_apex_tpu.utils.prefetch import BatchPrefetcher

        client = self.start_sampling(batch_size, beta_fn)

        def _sample():
            s = client.get()
            return s.idx, to_device(s)

        # depth=1: the wire client already pipelines sample_ahead_depth
        # requests; this stage only hides the host->device copy
        return BatchPrefetcher(_sample, depth=1, device_put=False,
                               registry=registry)

    def size(self) -> int:
        if self.sampler is not None:
            return self.sampler.size()
        return sum(p.size for p in self.peers.values())

    def sampleable(self) -> bool:
        if self.sampler is not None:
            return self.sampler.sampleable()
        return any(p.sampleable for p in self.peers.values())

    def update_priorities(self, idx: np.ndarray,
                          td_abs: np.ndarray) -> None:
        if self.sampler is not None:
            self.sampler.update_priorities(idx, td_abs)

    def flush_writebacks(self) -> None:
        """`WritebackRing` drain-boundary hook (``on_drain``)."""
        if self.sampler is not None:
            self.sampler.flush()

    # ------------------------------------------------------------ snapshots
    def request_snapshot(self, step: int) -> int:
        """Ask every alive peer to snapshot its shard block, fenced by the
        learner's checkpoint ``step``.  Returns how many acked; failures
        are logged, not raised (a dead peer snapshots when it readmits)."""
        ok = 0
        header: Dict[str, Any] = {"op": "snapshot", "step": int(step)}
        if self.learner_epoch is not None:
            header["learner_epoch"] = self.learner_epoch
        for pid, peer in list(self.peers.items()):
            if self.sampler is not None and pid in self.sampler.dead_peers():
                continue
            try:
                peer.request(dict(header), timeout_s=30.0)
                ok += 1
            except Exception as e:
                self._log("snapshot_failed", server=pid,
                          why=f"{type(e).__name__}: {e}")
        return ok

    # ----------------------------------------------------------- lifecycle
    def poll(self, step: int = 0) -> None:
        """Drive discovery + the drop/readmit lifecycle + the periodic
        stats row.  Call on the apex loop's metrics cadence (cheap: lease
        file reads + at most one bounded probe per peer)."""
        newly_dead, newly_alive = self.monitor.poll()
        for lease in newly_dead:
            if lease.role != _ROLE or lease.host not in self.peers:
                continue
            if self.sampler is not None:
                self.sampler.drop_peer(lease.host)
            self._log("peer_dead", server=lease.host, epoch=lease.epoch,
                      step=step)
        for lease in newly_alive:
            if lease.role != _ROLE or not lease.addr or not lease.port:
                continue
            known = self._peer_epoch.get(lease.host)
            if known is not None and int(lease.epoch) < known:
                # a stale lease file from a superseded incarnation: the
                # fence the in-process readmit_shard enforces, plane level
                self._log("stale_lease_ignored", server=lease.host,
                          epoch=lease.epoch, fenced_epoch=known)
                continue
            if lease.host in self.peers:
                peer = self._new_peer(lease)
                self.peers[lease.host] = peer
                self._peer_epoch[lease.host] = int(lease.epoch)
                if self.sampler is not None:
                    self.sampler.readmit_peer(lease.host, peer)
                ac = self._appenders.get(lease.host)
                if ac is not None:
                    ac.peer.close()
                    ac.peer = peer  # worker picks the new connection up
                self._log("peer_readmit", server=lease.host,
                          epoch=lease.epoch, step=step)
        self.discover()
        now = time.monotonic()
        if now - self._last_stats >= 10.0:
            self._last_stats = now
            self._stats_row(step)

    def _stats_row(self, step: int) -> None:
        dead = set(self.sampler.dead_peers()) if self.sampler else set()
        rtts = []
        for pid, peer in self.peers.items():
            if pid not in dead and peer.connected():
                rtt = peer.probe()
                if rtt is not None:
                    rtts.append(rtt)
        row: Dict[str, Any] = {
            "event": "stats", "step": step,
            "peers": len(self.peers), "dead_peers": len(dead),
            "size": self.size(),
            "rtt_ms": round(float(np.mean(rtts)), 3) if rtts else None,
            "shed_lanes": self.shed_lanes,
        }
        if self.sampler is not None:
            ss = self.sampler.stats()
            row.update(batches=self.sampler.batches_received,
                       rows_sampled=self.sampler.rows_sampled,
                       updates_sent=self.sampler.updates_sent,
                       updates_dropped=self.sampler.updates_dropped,
                       rerouted=self.sampler.rerouted,
                       # wire-transport attribution (critical-path
                       # analyzer): adaptive pipeline depth, negotiated
                       # batches-per-RPC, and measured RPC latencies
                       pipeline_depth=ss.get("depth"),
                       sample_many=ss.get("sample_many"),
                       sample_rtt_ms=ss.get("sample_rtt_ms"),
                       consume_gap_ms=ss.get("consume_gap_ms"),
                       wire_bytes_sent=sum(
                           p.bytes_sent for p in self.peers.values()),
                       wire_bytes_recv=sum(
                           p.bytes_recv for p in self.peers.values()))
        if self._appenders:
            row.update(
                spool_depth=sum(a.spool_depth()
                                for a in self._appenders.values()),
                acked_rows=sum(a.acked_rows
                               for a in self._appenders.values()),
                shed_ticks=sum(a.shed_ticks
                               for a in self._appenders.values()),
                fenced_rows=sum(a.fenced_rows
                                for a in self._appenders.values()))
        self._log(**row)

    def close(self) -> None:
        for ac in self._appenders.values():
            ac.flush(timeout_s=2.0)
            ac.close()
        self._appenders.clear()
        if self.sampler is not None:
            self.sampler.close()  # closes the shared peers
            self.sampler = None
        else:
            for peer in self.peers.values():
                peer.close()
        self.peers.clear()
