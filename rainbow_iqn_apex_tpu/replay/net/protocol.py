"""Wire vocabulary of the cross-host replay plane (replay/net/).

One replay shard server owns a contiguous block of global replay shards and
speaks the netcore frame protocol (netcore/framing.py — the same codec the
serving plane rides).  The ops:

    ping      -> pong          bounded liveness probe; teaches the client the
                               server's piggyback state (below)
    append    -> ack           a batched block of actor transitions: T lockstep
                               ticks x L lanes, epoch-stamped (see fencing)
    sample    -> batch         one assembled PER batch: uint8 obs/next_obs,
                               fp32 IS weights, GLOBAL slot indices
    update    -> ack           batched priority write-back at global indices,
                               epoch-stamped
    snapshot  -> ack           server-side replay snapshot, fenced by the
                               learner's checkpoint step (monotone)
    stats     -> stats_reply   lifetime counters for gates and obs rows
    rerr                       reasoned typed failure for any of the above

Fencing: every server incarnation carries the lease epoch it claimed at
startup (parallel/elastic.py ``next_lease_epoch``), and clients stamp the
epoch they last learned into ``append``/``update`` headers.  A respawned
server acks a stale-epoch write with ``fenced: true`` and DROPS the rows —
a dead incarnation's spool cannot resurrect priorities on the revived shard
block (the plane-level twin of ``ShardedReplay``'s per-shard write fence).

Piggyback contract (the serving plane's, replayed): every reply header
carries ``size``/``sampleable``/``mass``/``epoch``/``shard_base``/
``shards``/``capacity``, so the learner ranks and routes across N servers
with zero dedicated RPCs.

Indices on the wire are GLOBAL slot ids (``shard_base * shard_capacity +
local slot``): the server owns the translation, so a `SampleClient` mixing
batches from many servers hands `WritebackRing` exactly the id space the
in-process `ShardedReplay` would have.

jax-free (numpy + netcore only): actor spoolers and shard servers import
this without the device runtime.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

import numpy as np

from rainbow_iqn_apex_tpu.netcore import framing


class ReplayNetError(RuntimeError):
    """Base class for replay-plane transport failures."""


class PeerDead(ReplayNetError):
    """The connection to a replay shard server is gone (every in-flight
    request settles with this the moment the socket dies — the caller's
    survivors-only re-route path treats it like a shard drop)."""


# etype strings on the wire -> the exception the caller raises (mirrors the
# serving plane's _ETYPES so error handling stays transport-agnostic)
_ETYPES = {
    "empty": ValueError,  # all surviving shards empty: not sampleable yet
    "stale_fence": ValueError,  # snapshot step older than the fenced one
    "unsupported": RuntimeError,
    "dead": PeerDead,
}


def wire_error(etype: str, msg: str) -> BaseException:
    return _ETYPES.get(str(etype), ReplayNetError)(msg)


# Canonical column order of one append block (optional columns simply
# absent from the array set when the producer has none).
APPEND_COLS = ("frames", "actions", "rewards", "terminals",
               "priorities", "truncations")

# Canonical column set of one sampled batch reply (SampledBatch fields).
BATCH_COLS = ("idx", "obs", "action", "reward", "next_obs",
              "discount", "weight", "prob")


def encode_arrays(arrays: Dict[str, np.ndarray]
                  ) -> Tuple[List[Dict[str, Any]], bytes]:
    """(per-array meta list, packed blob) for a named array set.  Meta
    (name/dtype/shape) rides the frame header under ``arrays``; bytes ride
    the blob as a u32-length-prefixed chain in the same order."""
    metas: List[Dict[str, Any]] = []
    blobs: List[bytes] = []
    for name, arr in arrays.items():
        meta, raw = framing.encode_ndarray(np.asarray(arr))
        meta["name"] = str(name)
        metas.append(meta)
        blobs.append(raw)
    return metas, framing.pack_blobs(blobs)


def decode_arrays(metas: List[Dict[str, Any]],
                  blob: bytes) -> Dict[str, np.ndarray]:
    """Inverse of `encode_arrays`.  Arrays VIEW the blob (read-only);
    callers that mutate must copy."""
    raws = framing.unpack_blobs(blob)
    if len(raws) != len(metas):
        raise framing.FrameCorrupt(
            f"array frame declares {len(metas)} arrays, blob chain holds "
            f"{len(raws)}")
    return {str(m["name"]): framing.decode_ndarray(m, raw)
            for m, raw in zip(metas, raws)}
