"""Wire vocabulary of the cross-host replay plane (replay/net/).

One replay shard server owns a contiguous block of global replay shards and
speaks the netcore frame protocol (netcore/framing.py — the same codec the
serving plane rides).  The ops:

    ping      -> pong          bounded liveness probe; teaches the client the
                               server's piggyback state (below)
    append    -> ack           a batched block of actor transitions: T lockstep
                               ticks x L lanes, epoch-stamped (see fencing)
    sample    -> batch         one assembled PER batch: uint8 obs/next_obs,
                               fp32 IS weights, GLOBAL slot indices
    update    -> ack           batched priority write-back at global indices,
                               epoch-stamped
    snapshot  -> ack           server-side replay snapshot, fenced by the
                               learner's checkpoint step (monotone)
    stats     -> stats_reply   lifetime counters for gates and obs rows
    rerr                       reasoned typed failure for any of the above

Fencing: every server incarnation carries the lease epoch it claimed at
startup (parallel/elastic.py ``next_lease_epoch``), and clients stamp the
epoch they last learned into ``append``/``update`` headers.  A respawned
server acks a stale-epoch write with ``fenced: true`` and DROPS the rows —
a dead incarnation's spool cannot resurrect priorities on the revived shard
block (the plane-level twin of ``ShardedReplay``'s per-shard write fence).

Piggyback contract (the serving plane's, replayed): every reply header
carries ``size``/``sampleable``/``mass``/``epoch``/``shard_base``/
``shards``/``capacity``, so the learner ranks and routes across N servers
with zero dedicated RPCs.

Indices on the wire are GLOBAL slot ids (``shard_base * shard_capacity +
local slot``): the server owns the translation, so a `SampleClient` mixing
batches from many servers hands `WritebackRing` exactly the id space the
in-process `ShardedReplay` would have.

jax-free (numpy + netcore only): actor spoolers and shard servers import
this without the device runtime.
"""

from __future__ import annotations

import struct
from typing import Any, Dict, List, Sequence, Tuple

import numpy as np

from rainbow_iqn_apex_tpu.netcore import framing

# Request ops a replay shard server accepts (the reply vocabulary is
# pong/ack/batch/stats_reply/rerr).  analysis/wirecheck.py holds the
# server's dispatch table to exactly this tuple — adding an op here
# without handling it (or vice versa) fails the build.
OPS = ("ping", "append", "sample", "update", "snapshot", "stats")

# Highest batch wire-codec this build speaks.  v1 is the PR-16 format
# (encode_arrays: fp32/int64 columns, u32-length-prefixed blob chain);
# v2 is the compact codec below (u32 indices, fp16 IS weights/probs,
# palette-packed discounts, tight offset-addressed blob) plus the
# ``n``-batches-per-RPC ``sample`` form ("sample_many").  Negotiated via
# the piggyback ``wire`` field: a client never sends ``codec``/``n``
# until the server advertises ``wire >= 2``, and a server answers with
# the min of what was asked and what it speaks — old peers interop.
# Registered in netcore.framing.CODECS["replay_batch"]; wirecheck
# fails the build if the two constants drift.
WIRE_CODEC_MAX = 2


class ReplayNetError(RuntimeError):
    """Base class for replay-plane transport failures."""


class PeerDead(ReplayNetError):
    """The connection to a replay shard server is gone (every in-flight
    request settles with this the moment the socket dies — the caller's
    survivors-only re-route path treats it like a shard drop)."""


# etype strings on the wire -> the exception the caller raises (mirrors the
# serving plane's _ETYPES so error handling stays transport-agnostic)
_ETYPES = {
    "empty": ValueError,  # all surviving shards empty: not sampleable yet
    "stale_fence": ValueError,  # snapshot step older than the fenced one
    "unsupported": RuntimeError,
    "dead": PeerDead,
}


def wire_error(etype: str, msg: str) -> BaseException:
    return _ETYPES.get(str(etype), ReplayNetError)(msg)


# Canonical column order of one append block (optional columns simply
# absent from the array set when the producer has none).
APPEND_COLS = ("frames", "actions", "rewards", "terminals",
               "priorities", "truncations")

# Canonical column set of one sampled batch reply (SampledBatch fields).
BATCH_COLS = ("idx", "obs", "action", "reward", "next_obs",
              "discount", "weight", "prob")


def encode_arrays(arrays: Dict[str, np.ndarray]
                  ) -> Tuple[List[Dict[str, Any]], bytes]:
    """(per-array meta list, packed blob) for a named array set.  Meta
    (name/dtype/shape) rides the frame header under ``arrays``; bytes ride
    the blob as a u32-length-prefixed chain in the same order."""
    metas: List[Dict[str, Any]] = []
    blobs: List[bytes] = []
    for name, arr in arrays.items():
        meta, raw = framing.encode_ndarray(np.asarray(arr))
        meta["name"] = str(name)
        metas.append(meta)
        blobs.append(raw)
    return metas, framing.pack_blobs(blobs)


def decode_arrays(metas: List[Dict[str, Any]],
                  blob: bytes) -> Dict[str, np.ndarray]:
    """Inverse of `encode_arrays`.  Arrays VIEW the blob (read-only);
    callers that mutate must copy.  Accepts a memoryview as ``blob`` —
    the `recv_frame_view` path — in which case the views are zero-copy
    all the way down to the socket's receive buffer."""
    raws = framing.unpack_blobs(blob)
    if len(raws) != len(metas):
        raise framing.FrameCorrupt(
            f"array frame declares {len(metas)} arrays, blob chain holds "
            f"{len(raws)}")
    return {str(m["name"]): framing.decode_ndarray(m, raw)
            for m, raw in zip(metas, raws)}


def encode_arrays_views(arrays: Dict[str, np.ndarray]
                        ) -> Tuple[List[Dict[str, Any]], List[Any]]:
    """Zero-copy twin of `encode_arrays`: same v1 wire bytes (u32-prefixed
    chain, decodable by `decode_arrays` on any peer), but the arrays ride
    as memoryviews for `framing.send_frame_views` instead of being copied
    through ``tobytes`` + ``pack_blobs``."""
    metas: List[Dict[str, Any]] = []
    blobs: List[Any] = []
    for name, arr in arrays.items():
        arr = np.asarray(arr)
        view = framing.ndarray_view(arr)
        metas.append({"dtype": str(arr.dtype), "shape": list(arr.shape),
                      "name": str(name)})
        blobs.append(struct.pack(">I", view.nbytes))
        blobs.append(view)
    return metas, blobs


# ------------------------------------------------------- compact codec (v2)
#
# One v2 column meta is {name, dtype, shape, enc, nbytes, [scale|palette]}:
# ``dtype``/``shape`` describe the DECODED array, ``enc`` how its bytes are
# packed on the wire, ``nbytes`` how many wire bytes it occupies — columns
# are tightly concatenated in meta order (no per-column length prefixes;
# offsets are implied), so the whole batch decodes by walking one buffer.
#
# Encodings (V2_ENCODINGS is the closed set; wirecheck holds the decoder
# table to it):
#   raw   verbatim bytes (uint8 obs/next_obs, actions, rewards — already
#         minimal, and bit-faithfulness is the contract)
#   u32   int64 slot indices as uint32 — EXACT (falls back to raw if any
#         index overflows 32 bits; capacity*shards past 4Gi slots)
#   f16   float as IEEE fp16 (values known to sit in fp16's sweet range)
#   f16s  max-scaled fp16: wire carries value/scale at fp16 plus one f64
#         ``scale`` in the meta — IS weights and probs keep < ~5e-4
#         relative error regardless of their absolute magnitude
#   pal1  <=2 distinct values, 1 bit per element + exact-value palette
#         (discount columns are {0, gamma^n} almost always) — LOSSLESS
#   pal8  <=256 distinct values, u8 index + palette — LOSSLESS
V2_ENCODINGS = ("raw", "u32", "f16", "f16s", "pal1", "pal8")

# columns eligible for lossy fp16 packing; everything else must survive
# bit-faithfully (obs pixels, actions, rewards feed the loss directly)
_F16_COLS = frozenset({"weight", "prob"})
_PALETTE_COLS = frozenset({"discount"})
_U32_COLS = frozenset({"idx"})


def _enc_col(name: str, arr: np.ndarray) -> Tuple[Dict[str, Any], Any]:
    arr = np.asarray(arr)
    meta: Dict[str, Any] = {"name": str(name), "dtype": str(arr.dtype),
                            "shape": list(arr.shape)}
    if name in _U32_COLS and arr.dtype.kind in "iu" and arr.size:
        lo, hi = int(arr.min()), int(arr.max())
        if 0 <= lo and hi < (1 << 32):
            wire = np.ascontiguousarray(arr, dtype=np.int64
                                        ).astype(np.uint32)
            meta.update(enc="u32", nbytes=wire.nbytes)
            return meta, framing.ndarray_view(wire)
    elif name in _PALETTE_COLS and arr.dtype.kind == "f" and arr.size:
        palette = np.unique(arr)
        if palette.size <= 2:
            lut = np.searchsorted(palette, arr.ravel()).astype(np.uint8)
            wire = np.packbits(lut)
            meta.update(enc="pal1", nbytes=wire.nbytes,
                        palette=[float(v) for v in palette])
            return meta, framing.ndarray_view(wire)
        if palette.size <= 256:
            wire = np.searchsorted(palette, arr.ravel()).astype(np.uint8)
            meta.update(enc="pal8", nbytes=wire.nbytes,
                        palette=[float(v) for v in palette])
            return meta, framing.ndarray_view(wire)
    elif name in _F16_COLS and arr.dtype.kind == "f" and arr.size:
        scale = float(np.max(np.abs(arr)))
        if scale <= 0.0 or not np.isfinite(scale):
            scale = 1.0
        wire = (arr / scale).astype(np.float16)
        meta.update(enc="f16s", nbytes=wire.nbytes, scale=scale)
        return meta, framing.ndarray_view(wire)
    view = framing.ndarray_view(np.ascontiguousarray(arr))
    meta.update(enc="raw", nbytes=view.nbytes)
    return meta, view


def _dec_raw(meta, buf, dtype, shape):
    return np.frombuffer(buf, dtype=dtype).reshape(shape)


def _dec_u32(meta, buf, dtype, shape):
    return np.frombuffer(buf, dtype=np.uint32).astype(dtype).reshape(shape)


def _dec_f16(meta, buf, dtype, shape):
    return np.frombuffer(buf, dtype=np.float16).astype(dtype).reshape(shape)


def _dec_f16s(meta, buf, dtype, shape):
    vals = np.frombuffer(buf, dtype=np.float16).astype(dtype)
    return (vals * dtype.type(meta["scale"])).reshape(shape)


def _dec_pal1(meta, buf, dtype, shape):
    n = int(np.prod(shape, dtype=np.int64))
    palette = np.asarray(meta["palette"], dtype=dtype)
    if palette.size == 0:
        return np.zeros(shape, dtype=dtype)
    bits = np.unpackbits(np.frombuffer(buf, dtype=np.uint8), count=n)
    return palette[np.minimum(bits, palette.size - 1)].reshape(shape)


def _dec_pal8(meta, buf, dtype, shape):
    palette = np.asarray(meta["palette"], dtype=dtype)
    lut = np.frombuffer(buf, dtype=np.uint8)
    if lut.size and palette.size and int(lut.max()) >= palette.size:
        raise framing.FrameCorrupt(
            f"pal8 column {meta.get('name')!r} indexes past its "
            f"{palette.size}-entry palette")
    return palette[lut].reshape(shape)


_V2_DECODERS = {
    "raw": _dec_raw,
    "u32": _dec_u32,
    "f16": _dec_f16,
    "f16s": _dec_f16s,
    "pal1": _dec_pal1,
    "pal8": _dec_pal8,
}


def encode_batch_v2(arrays: Dict[str, np.ndarray], sums: bool = True
                    ) -> Tuple[List[Dict[str, Any]], List[Any]]:
    """(metas, wire buffers) for one sampled batch under codec v2.  The
    buffers concatenate into the frame blob with no interleaved framing;
    feed them straight to `framing.send_frame_views` with
    ``crc_blob=False``: every meta carries the column's `word_sum64`, so
    the batch checks its own integrity (verified at decode) and the frame
    envelope skips the ~1 GB/s blob CRC that would otherwise dominate the
    wire path's CPU.  ``sums=False`` omits the stamps — for batches that
    never traverse a wire (the same-host shared-memory arena, shm.py)."""
    metas: List[Dict[str, Any]] = []
    buffers: List[Any] = []
    for name, arr in arrays.items():
        meta, buf = _enc_col(name, arr)
        if sums:
            meta["sum64"] = framing.word_sum64(buf)
        metas.append(meta)
        buffers.append(buf)
    return metas, buffers


def decode_batch_v2(metas: Sequence[Dict[str, Any]], blob,
                    offset: int = 0) -> Dict[str, np.ndarray]:
    """Inverse of `encode_batch_v2` over ``blob[offset:]``.  ``raw``
    columns VIEW the blob (read-only — pass a memoryview to stay
    zero-copy); transformed columns (u32/f16*/pal*) decode into small
    OWNED arrays, so holding e.g. ``idx`` never pins the frame buffer."""
    out: Dict[str, np.ndarray] = {}
    off = int(offset)
    total = len(blob)
    for meta in metas:
        enc = str(meta.get("enc", "raw"))
        dec = _V2_DECODERS.get(enc)
        if dec is None:
            raise framing.FrameCorrupt(
                f"batch column {meta.get('name')!r} uses unknown encoding "
                f"{enc!r} (peer speaks a newer codec than it negotiated)")
        nbytes = int(meta["nbytes"])
        if off + nbytes > total:
            raise framing.FrameCorrupt(
                f"batch blob truncated in column {meta.get('name')!r}: "
                f"needs {nbytes} bytes at offset {off}, {total - off} remain")
        dtype = np.dtype(str(meta["dtype"]))
        shape = tuple(int(d) for d in meta["shape"])
        buf = blob[off:off + nbytes]
        want = meta.get("sum64")
        if want is not None and framing.word_sum64(buf) != int(want):
            raise framing.FrameCorrupt(
                f"batch column {meta.get('name')!r} word-sum mismatch: "
                "wire bytes were damaged in flight (v2 frames delegate "
                "blob integrity to this per-column check)")
        out[str(meta["name"])] = dec(meta, buf, dtype, shape)
        off += nbytes
    return out


def batches_nbytes(metas_list: Sequence[Sequence[Dict[str, Any]]]) -> int:
    """Total wire bytes a v2 multi-batch blob occupies (for offset walks
    and telemetry)."""
    return sum(int(m["nbytes"]) for metas in metas_list for m in metas)


def decode_batches_v2(metas_list: Sequence[Sequence[Dict[str, Any]]],
                      blob) -> List[Dict[str, np.ndarray]]:
    """Decode the ``sample_many`` reply form: N batches' metas under the
    header's ``batches`` key, their wire bytes tightly concatenated in
    the one frame blob."""
    out: List[Dict[str, np.ndarray]] = []
    off = 0
    for metas in metas_list:
        out.append(decode_batch_v2(metas, blob, off))
        off += sum(int(m["nbytes"]) for m in metas)
    return out
