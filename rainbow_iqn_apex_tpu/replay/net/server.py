"""Server half of the cross-host replay plane: `ReplayShardServer` owns one
contiguous block of global replay shards (a `ShardedReplay` built for just
that block) and speaks the netcore frame protocol to N clients — actor-side
`AppendClient`s feeding transitions in, one learner-side `SampleClient`
draining assembled batches and writing priorities back.

Topology (the Ape-X/Redis-shard picture, now actually disaggregated): the
global replay of ``R`` shards is split into per-server blocks; a server
constructed with ``shard_base=b`` owning ``S`` local shards serves global
shards ``[b, b+S)`` and global slot ids ``[b*C, (b+S)*C)`` — it translates
at the wire boundary, so clients and the learner's `WritebackRing` see the
SAME global id space the in-process `ShardedReplay` exposes.

Concurrency: the selectors-driven event loop (the serving plane's
`TransportServer` shape — accepts + reads on one daemon thread, replies
drained by per-connection writer threads) never touches the replay memory.
ALL memory ops (append/sample/update/snapshot) funnel through ONE worker
thread via a bounded work queue — `ShardedReplay` is not thread-safe, and
serialising writers is exactly the single-redis-instance semantics each
shard block already models.  Pings and stats answer inline on the loop, so
liveness probes stay bounded behind a slow sample.

Fencing: the server carries the lease epoch its incarnation claimed
(``next_lease_epoch``); ``append``/``update`` frames stamped with an OLDER
epoch are acked ``fenced: true`` and dropped — a respawned server's
clients cannot resurrect a dead incarnation's spool into the revived shard
block.  Acks are sent AFTER the memory op lands (worker-thread ordering),
so an acked append is durably in the ring: the zero-loss gate the smoke
(scripts/replay_net_smoke.py) asserts counts exactly these.

Snapshots run server-side (``snapshot`` op), fenced by the learner's
checkpoint step: a replayed or reordered snapshot request older than the
last fenced step is refused, and a restarting server restores its own shard
block from its snapshot prefix before accepting traffic.

jax-free (numpy + netcore + replay host structures): a shard server is a
DRAM process, never a device one.
"""

from __future__ import annotations

import collections
import os
import queue
import selectors
import socket
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from rainbow_iqn_apex_tpu.netcore import chaos, framing
from rainbow_iqn_apex_tpu.replay.net import protocol, shm

# bound on one reply write: a peer that stalls reading for this long is
# dropped (its requests settle as PeerDead client-side) instead of wedging
# the writing thread
_SEND_TIMEOUT_S = 5.0
# bound on queued memory ops: a client pipelining far past the worker's
# drain rate is backpressured by its own acks, so a full queue means a
# runaway peer — shed the op with a reasoned rerr instead of growing
_WORK_QUEUE_DEPTH = 256
# cap on batches per sample_many RPC: bounds one reply frame (16 Atari
# batches ~ 29 MB, still under the default 64 MiB frame bound) and bounds
# how stale a pre-assembled batch's beta can run
_SAMPLE_MANY_MAX = 16
# a ring entry built at beta b still answers a request at beta b' when
# |b-b'| is under this: beta anneals over millions of steps, so the drift
# across one ring's lifetime is orders of magnitude smaller
_BETA_SLACK = 0.05
# telemetry cadence of the per-op wire-bytes / ring-depth row
_STATS_ROW_PERIOD_S = 10.0


class _Conn:
    """One accepted client connection: socket, incremental frame reader,
    and a bounded outbound queue drained by this connection's OWN writer
    thread (neither the selector loop nor the memory worker ever blocks on
    a peer's full send buffer).

    ``ring`` is this connection's sample-ahead buffer: pre-assembled,
    pre-ENCODED batches (codec, beta, metas, wire buffers) built by the
    memory worker after each sample, so the NEXT ``sample`` request is
    answered straight from the event loop — no memory access, no encode,
    no queue wait behind appends.  ``ring_want`` is the last request shape
    (batch, beta, codec) the refill targets; entries that no longer match
    are discarded on pop.  All ring state is guarded by the server lock.

    ``pre`` accumulates the 16-byte shm preamble on AF_UNIX connections
    (None once consumed, and always None on TCP); ``arena`` is this
    connection's shared-memory slot arena when the preamble negotiated one
    (replay/net/shm.py) — it lives and dies with the connection."""

    __slots__ = ("sock", "reader", "peer", "outq", "ring", "ring_want",
                 "pre", "arena")

    def __init__(self, sock: socket.socket, max_frame_bytes: int,
                 unix: bool = False):
        self.sock = sock
        self.reader = framing.FrameReader(max_frame_bytes)
        self.outq: "queue.Queue" = queue.Queue(maxsize=4096)
        self.ring: "collections.deque" = collections.deque()
        self.ring_want: Optional[Tuple[int, float, int]] = None
        self.pre: Optional[bytearray] = bytearray() if unix else None
        self.arena: Optional[shm.ServerArena] = None
        if unix:
            self.peer = f"unix:{sock.fileno()}"
            return
        try:
            self.peer = "%s:%s" % sock.getpeername()[:2]
        except OSError:
            self.peer = "?"


def _fd(conn: _Conn) -> int:
    try:
        return conn.sock.fileno()
    except OSError:
        return -1


class ReplayShardServer:
    """Serve one shard block of the global replay over the framed protocol.

    ``memory`` is the `ShardedReplay` this server owns (its local shard 0 is
    global shard ``shard_base``); ``epoch`` is the lease epoch of this
    incarnation (stamp from ``next_lease_epoch`` in deployments — the write
    fence clients are checked against).  ``port=0`` binds an ephemeral port
    (read ``.port``); ``snapshot_prefix`` enables the server-side
    ``snapshot`` op and the restore-on-start path.
    """

    def __init__(self, memory: Any, shard_base: int = 0,
                 host: str = "127.0.0.1", port: int = 0,
                 advertise: Optional[str] = None,
                 max_frame_bytes: int = framing.DEFAULT_MAX_FRAME,
                 epoch: int = 0, snapshot_prefix: Optional[str] = None,
                 ring_depth: int = 2, shm_mb: int = 64,
                 local_fastpath: bool = True, logger=None):
        self.memory = memory
        self.ring_depth = max(int(ring_depth), 0)  # 0 disables sample-ahead
        self.shm_mb = max(int(shm_mb), 0)  # 0 disables arenas (unix-only)
        self.shard_base = int(shard_base)
        self.slot_base = self.shard_base * memory.shard_capacity
        self.epoch = int(epoch)
        self.snapshot_prefix = snapshot_prefix
        self.max_frame_bytes = int(max_frame_bytes)
        self.logger = logger
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, int(port)))
        self._listener.listen(64)
        self._listener.setblocking(False)
        self.port = self._listener.getsockname()[1]
        self.advertise = advertise or (
            "127.0.0.1" if host in ("", "0.0.0.0") else host)
        self._selector = selectors.DefaultSelector()
        self._selector.register(self._listener, selectors.EVENT_READ, None)
        # same-host fast path (replay/net/shm.py): an abstract AF_UNIX
        # listener beside the TCP port.  Colocated clients dial it for the
        # kernel-copy-free arena path; everything else keeps TCP.  Best
        # effort — any failure leaves the TCP-only server intact.
        self._ulistener: Optional[socket.socket] = None
        if local_fastpath and shm.available():
            try:
                ul = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                ul.bind(shm.unix_path(self.port))
                ul.listen(64)
                ul.setblocking(False)
                self._ulistener = ul
                self._selector.register(ul, selectors.EVENT_READ, None)
            except OSError:
                self._ulistener = None
        self._conns: Dict[int, _Conn] = {}  # fd -> conn
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._worker: Optional[threading.Thread] = None
        self._work: "queue.Queue" = queue.Queue(maxsize=_WORK_QUEUE_DEPTH)
        # lifetime counters (the smoke's gates + the stats op)
        self.frames_in = 0
        self.bytes_out = 0
        self.rows_appended = 0  # acked-and-landed transition rows
        self.fenced_appends = 0
        self.fenced_updates = 0
        self.samples_served = 0
        self.updates_applied = 0
        # sample-ahead + wire accounting (satellite: per-op wire bytes and
        # ring depth flow to obs/net so learner stalls attribute to replay
        # transport); ring_hits counts sample requests answered from a
        # connection's pre-assembled ring, bytes_by_op the reply bytes per
        # reply op — both under self._lock (written from the event loop,
        # worker, AND writer threads)
        self.ring_hits = 0
        self._bytes_by_op: Dict[str, int] = {}
        self._last_stats_row = time.monotonic()
        self.snapshot_step = -1
        # learner-role epoch latch (parallel/failover.py): priority
        # write-backs and snapshot requests stamped by a SUPERSEDED learner
        # incarnation are refused — the step fence below grown an epoch
        # dimension.  -1 = no failover-armed learner ever wrote; unstamped
        # frames (every pre-failover client) always pass, so the off path
        # is bitwise intact.  Persisted beside the snapshot step so a
        # restarted server cannot be rolled back by a patient zombie.
        self.learner_epoch = -1
        self.fenced_learner_writes = 0
        # advisory piggyback state: written by the worker after each memory
        # op, read (under the lock) by every reply — the event loop never
        # touches the un-thread-safe memory itself
        self._adv: Dict[str, Any] = {}
        # live fleet telemetry (obs/net/): from_config attaches a relay so
        # a disaggregated replay host shows up on the fleet dashboard like
        # every other role; None on the default path and direct constructs
        self.obs_relay = None
        self._refresh_advisory()
        if snapshot_prefix is not None:
            self._maybe_restore()

    @classmethod
    def from_config(cls, cfg, memory: Any, epoch: int = 0,
                    snapshot_prefix: Optional[str] = None,
                    logger=None) -> Optional["ReplayShardServer"]:
        """The config seam: ``replay_net_host`` unset (default) returns None
        — replay stays in-process, bitwise the pre-net path."""
        if not getattr(cfg, "replay_net_host", ""):
            return None
        srv = cls(
            memory, shard_base=int(cfg.replay_net_shard_base),
            host=cfg.replay_net_host, port=cfg.replay_net_port,
            advertise=cfg.replay_net_advertise or None,
            max_frame_bytes=int(cfg.replay_net_max_frame_mb) << 20,
            epoch=epoch, snapshot_prefix=snapshot_prefix,
            ring_depth=int(getattr(cfg, "replay_net_ring_depth", 2)),
            shm_mb=int(getattr(cfg, "replay_net_shm_mb", 64)),
            local_fastpath=bool(
                getattr(cfg, "replay_net_local_fastpath", True)),
            logger=logger)
        if logger is not None and getattr(cfg, "obs_net", False):
            from rainbow_iqn_apex_tpu.obs.net.relay import ObsRelay

            srv.obs_relay = ObsRelay.attach(cfg, logger, role="replay_shard")
        return srv

    def attach_lease(self, writer) -> None:
        """Advertise ``addr:port`` (and the shard block) in this server's
        lease payload so clients discover the endpoint through the lease
        files they already watch — no second discovery protocol.  Call
        BEFORE ``writer.start()`` so the very first beat carries it."""
        writer.update_payload(addr=self.advertise, port=self.port,
                              shard_base=self.shard_base,
                              shards=len(self.memory.shards))

    # -------------------------------------------------------------- lifecycle
    def start(self) -> "ReplayShardServer":
        if self._thread is None:
            self._worker = threading.Thread(
                target=self._work_loop, name=f"replaynet-mem-{self.port}",
                daemon=True)
            self._worker.start()
            self._thread = threading.Thread(
                target=self._run, name=f"replaynet-server-{self.port}",
                daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        """Close the listener and every connection.  Clients see the drop
        as `PeerDead` and re-route to survivors — the wire analog of
        ``drop_shard``."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        if self._worker is not None:
            try:
                self._work.put_nowait(None)
            except queue.Full:
                pass
            self._worker.join(timeout=10)
            self._worker = None
        with self._lock:
            conns = list(self._conns.values())
            self._conns.clear()
        for conn in conns:
            self._close_conn(conn, unregister=False)
        try:
            self._selector.close()
        except (OSError, RuntimeError):
            pass
        try:
            self._listener.close()
        except OSError:
            pass
        if self._ulistener is not None:
            try:
                self._ulistener.close()
            except OSError:
                pass
        if self.obs_relay is not None:
            self.obs_relay.close()
            self.obs_relay = None

    # -------------------------------------------------------------- event loop
    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                events = self._selector.select(timeout=0.1)
            except OSError:
                return
            for key, _mask in events:
                if key.data is None:  # one of the two listeners
                    self._accept(key.fileobj)
                else:
                    self._read(key.data)
            self._maybe_stats_row()

    def _maybe_stats_row(self) -> None:
        """Rate-limited wire-telemetry row: per-op reply bytes + ring
        depth, the numbers the critical-path analyzer needs to attribute a
        learner stall to replay transport.  Event-loop only."""
        if self.logger is None:
            return
        now = time.monotonic()
        if now - self._last_stats_row < _STATS_ROW_PERIOD_S:
            return
        self._last_stats_row = now
        with self._lock:
            by_op = dict(self._bytes_by_op)
            ring = sum(len(c.ring) for c in self._conns.values())
            conns = len(self._conns)
            shm_conns = sum(1 for c in self._conns.values()
                            if c.arena is not None)
            shm_free = sum(len(c.arena.free) for c in self._conns.values()
                           if c.arena is not None)
        self._log("wire", bytes_out=self.bytes_out, bytes_by_op=by_op,
                  ring_depth=ring, ring_hits=self.ring_hits,
                  samples_served=self.samples_served,
                  connections=conns, shm_conns=shm_conns,
                  shm_slots_free=shm_free, shard_base=self.shard_base)

    def _accept(self, listener) -> None:
        unix = listener is self._ulistener
        try:
            sock, _addr = listener.accept()
        except OSError:
            return
        # blocking with a bound (see TransportServer._accept): sendall
        # loops through partial writes; only a peer stalled past the bound
        # is dropped.  Reads stay selector-driven.
        sock.settimeout(_SEND_TIMEOUT_S)
        if unix:
            peer_label = f"unix:{sock.fileno()}"
        else:
            peer_label = f"{_addr[0]}:{_addr[1]}"
            try:
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass
        sock = chaos.maybe_wrap(sock, peer=peer_label, logger=self.logger)
        conn = _Conn(sock, self.max_frame_bytes, unix=unix)
        with self._lock:
            self._conns[sock.fileno()] = conn
        threading.Thread(target=self._write_loop, args=(conn,),
                         name=f"replaynet-writer-{self.port}",
                         daemon=True).start()
        self._selector.register(sock, selectors.EVENT_READ, conn)

    def _close_conn(self, conn: _Conn, unregister: bool = True) -> None:
        if unregister:
            try:
                self._selector.unregister(conn.sock)
            except (KeyError, OSError, ValueError):
                pass
            with self._lock:
                self._conns.pop(conn.sock.fileno(), None)
        try:
            conn.outq.put_nowait(None)  # stop the writer thread
        except queue.Full:
            pass  # writer will exit on the closed socket's send error
        try:
            conn.sock.close()
        except OSError:
            pass
        if conn.arena is not None:
            with self._lock:
                conn.arena.close()
                conn.arena = None

    def _shm_handshake(self, conn: _Conn) -> bool:
        """Consume the 16-byte preamble an AF_UNIX client leads with and
        answer the hello (+ memfd via SCM_RIGHTS when an arena was both
        requested and enabled).  True on success; False closes the conn.
        The reply is sent inline from the event loop — it is 16 bytes into
        an empty socket buffer, and no frame traffic exists yet."""
        pre = conn.pre
        assert pre is not None
        flags = shm.parse_request(bytes(pre[:shm.PREAMBLE_BYTES]))
        if flags is None:
            self._log("bad_preamble", peer=conn.peer)
            return False
        if flags & shm.FLAG_WANT_ARENA and self.shm_mb > 0:
            arena, fd = shm.ServerArena.create(self.shm_mb << 20)
            try:
                # ChaosSocket passes ancdata sends through untouched
                socket.send_fds(conn.sock, [shm.pack_hello(arena.nbytes)],
                                [fd])
            except OSError:
                arena.close()
                return False
            finally:
                os.close(fd)
            with self._lock:
                conn.arena = arena
        else:
            try:
                conn.sock.sendall(shm.pack_hello(0))
            except OSError:
                return False
        rest = bytes(pre[shm.PREAMBLE_BYTES:])
        conn.pre = None
        if rest:
            for header, blob in conn.reader.feed(rest):
                self.frames_in += 1
                self._handle(conn, header, blob)
        return True

    def _read(self, conn: _Conn) -> None:
        try:
            data = conn.sock.recv(1 << 16)
        except (BlockingIOError, socket.timeout):
            return  # spurious readiness; nothing to read this round
        except OSError:
            self._close_conn(conn)
            return
        if not data:
            self._close_conn(conn)
            return
        if conn.pre is not None:  # AF_UNIX conn still mid-preamble
            conn.pre += data
            if len(conn.pre) < shm.PREAMBLE_BYTES:
                return
            try:
                ok = self._shm_handshake(conn)
            except (OSError, framing.FrameError, ValueError):
                ok = False
            if not ok:
                self._close_conn(conn)
            return
        try:
            frames = conn.reader.feed(data)
        except framing.FrameError as e:
            # torn/corrupt/oversize append frame: the CRC trailer caught it
            # BEFORE any rows landed — drop the connection with one
            # reasoned row; the client's spool re-ships after reconnect
            # (docs/RESILIENCE.md, "torn append frame")
            self._log("bad_frame", peer=conn.peer,
                      why=f"{type(e).__name__}: {e}")
            self._close_conn(conn)
            return
        for header, blob in frames:
            self.frames_in += 1
            try:
                self._handle(conn, header, blob)
            except Exception as e:
                self._reply(conn, {"op": "rerr",
                                   "rid": header.get("rid"),
                                   "etype": "dead",
                                   "msg": f"{type(e).__name__}: {e}"})

    # ---------------------------------------------------------------- replies
    def _log(self, event: str, **fields: Any) -> None:
        if self.logger is not None:
            try:
                self.logger.log("replay_net", event=event, **fields)
            except Exception:
                pass

    def _refresh_advisory(self) -> None:
        """Recompute the piggyback state from the memory.  WORKER-thread
        only (plus construction, before any thread exists) — replies read
        the cached copy under the lock."""
        mem = self.memory
        alive = [s for k, s in enumerate(mem.shards)
                 if k not in mem._dead]  # advisory read; worker-serialised
        adv = {
            "size": sum(len(s) for s in alive),
            "sampleable": bool(mem.sampleable),
            "mass": float(sum(s.tree.total for s in alive)),
            "epoch": self.epoch,
            "shard_base": self.shard_base,
            "shards": len(mem.shards),
            "capacity": int(mem.shard_capacity),
            # codec negotiation: clients never send ``codec``/``n`` until
            # they have seen this (old servers simply lack the key -> v1)
            "wire": protocol.WIRE_CODEC_MAX,
        }
        with self._lock:
            self._adv = adv

    def _state(self) -> Dict[str, Any]:
        with self._lock:
            return dict(self._adv)

    def _reply(self, conn: _Conn, header: Dict[str, Any],
               blob: Any = b"", crc_blob: bool = True) -> None:
        """Enqueue one reply for the connection's writer thread (the event
        loop and the memory worker never touch the socket).  ``blob`` is
        either bytes or a LIST of buffers for the zero-copy vectored send;
        ``crc_blob=False`` sends a v2 delegated-integrity frame (codec-v2
        batches only — their columns carry word-sums).  A full queue means
        the peer is long stalled — drop it instead of growing."""
        header = {**header, **self._state()}
        try:
            conn.outq.put_nowait((header, blob, crc_blob))
        except queue.Full:
            self._close_conn(conn)

    def _write_loop(self, conn: _Conn) -> None:
        while True:
            item = conn.outq.get()
            if item is None:  # close sentinel
                return
            header, blob, crc_blob = item
            buffers = blob if isinstance(blob, list) else [blob]
            try:
                n = framing.send_frame_views(conn.sock, header, buffers,
                                             crc_blob=crc_blob)
            except (OSError, ValueError, framing.FrameError):
                self._close_conn(conn)
                return
            self.bytes_out += n
            op = str(header.get("op"))
            with self._lock:
                self._bytes_by_op[op] = self._bytes_by_op.get(op, 0) + n

    # ---------------------------------------------------------------- handlers
    def _handle(self, conn: _Conn, header: Dict[str, Any],
                blob: bytes) -> None:
        op = header.get("op")
        rid = header.get("rid")
        if op == "ping":
            self._reply(conn, {"op": "pong", "rid": rid, "alive": True})
        elif op == "stats":
            self._reply(conn, {"op": "stats_reply", "rid": rid,
                               **self.stats()})
        elif op in ("append", "sample", "update", "snapshot"):
            if op == "sample":
                # the client returns consumed arena slots on its NEXT
                # sample request (deferred by its hold window, so the
                # learner's zero-copy views are never overwritten mid-read)
                freed = header.get("free")
                if freed and conn.arena is not None:
                    with self._lock:
                        for off in freed:
                            conn.arena.release(off)
                if self._ring_serve(conn, rid, header):
                    return  # answered from the sample-ahead ring
            # memory ops run on the ONE worker thread; the bounded queue
            # sheds a runaway pipeliner with a reasoned rerr instead of
            # buffering without bound
            try:
                self._work.put_nowait((conn, op, rid, header, blob))
            except queue.Full:
                self._reply(conn, {"op": "rerr", "rid": rid,
                                   "etype": "unsupported",
                                   "msg": "server work queue full (client "
                                          "pipelining past the drain rate)"})
        else:
            self._reply(conn, {"op": "rerr", "rid": rid,
                               "etype": "unsupported",
                               "msg": f"unknown op {op!r}"})

    def _work_loop(self) -> None:
        while True:
            item = self._work.get()
            if item is None:
                return
            conn, op, rid, header, blob = item
            try:
                if op == "append":
                    self._do_append(conn, rid, header, blob)
                elif op == "sample":
                    self._do_sample(conn, rid, header)
                elif op == "update":
                    self._do_update(conn, rid, header, blob)
                elif op == "refill":
                    # opportunistic sample-ahead top-up after a ring hit;
                    # no reply, no advisory change
                    self._refill(conn)
                    continue
                else:
                    self._do_snapshot(conn, rid, header)
                self._refresh_advisory()
            except Exception as e:
                self._reply(conn, {"op": "rerr", "rid": rid,
                                   "etype": "dead",
                                   "msg": f"{type(e).__name__}: {e}"})

    def _fenced(self, header: Dict[str, Any]) -> bool:
        """True when the frame's epoch stamp names a STALE incarnation of
        this shard block (the respawned-server split-brain fence).  A frame
        with no epoch — a client that has not learned one yet — passes, the
        same ``epoch=None`` contract `ShardedReplay._fence` keeps."""
        epoch = header.get("epoch")
        return epoch is not None and int(epoch) != self.epoch

    def _stale_learner(self, header: Dict[str, Any]) -> bool:
        """True when the frame's ``learner_epoch`` stamp names a SUPERSEDED
        learner incarnation (the zombie fence — docs/RESILIENCE.md "zombie
        learner").  Unstamped frames pass; a NEWER stamp latches (and
        persists) the new floor, so once the successor's first write lands
        the predecessor is refused forever, restarts included."""
        le = header.get("learner_epoch")
        if le is None:
            return False
        le = int(le)
        if le < self.learner_epoch:
            self.fenced_learner_writes += 1
            self._log("stale_learner", learner_epoch=le,
                      latched=self.learner_epoch)
            return True
        if le > self.learner_epoch:
            self.learner_epoch = le
            if self.snapshot_prefix is not None:
                self._write_learner_epoch(le)
        return False

    def _do_append(self, conn: _Conn, rid: Any, header: Dict[str, Any],
                   blob: bytes) -> None:
        if self._fenced(header):
            self.fenced_appends += 1
            self._reply(conn, {"op": "ack", "rid": rid, "ok": False,
                               "fenced": True})
            return
        arrays = protocol.decode_arrays(header.get("arrays", ()), blob)
        frames, actions = arrays["frames"], arrays["actions"]
        ticks = int(header.get("ticks", 1))
        if ticks <= 0 or actions.shape[0] != ticks:
            raise ValueError(
                f"append block declares {ticks} ticks, arrays carry "
                f"{actions.shape[0]}")
        pri = arrays.get("priorities")
        trunc = arrays.get("truncations")
        rows = 0
        for t in range(ticks):
            # each tick is one lockstep lane append: ring order inside the
            # block is exactly the order the producer experienced
            self.memory.append_batch(
                frames[t], actions[t], arrays["rewards"][t],
                arrays["terminals"][t],
                None if pri is None else pri[t],
                None if trunc is None else trunc[t])
            rows += int(actions[t].shape[0])
        self.rows_appended += rows
        self._reply(conn, {"op": "ack", "rid": rid, "ok": True,
                           "rows": rows})

    @staticmethod
    def _negotiate(header: Dict[str, Any]) -> Tuple[int, int, int, float]:
        """(codec, n, batch, beta) for one sample request: codec capped at
        what this build speaks (a newer client degrades gracefully), the
        batches-per-RPC count forced to 1 under v1 and bounded under v2."""
        codec = min(int(header.get("codec", 1)), protocol.WIRE_CODEC_MAX)
        n = int(header.get("n", 1)) if codec >= 2 else 1
        n = max(1, min(n, _SAMPLE_MANY_MAX))
        return codec, n, int(header["batch"]), float(header["beta"])

    @staticmethod
    def _entry_matches(entry, codec: int, batch: int, beta: float) -> bool:
        return (entry[0] == codec and entry[1] == batch
                and abs(entry[2] - beta) <= _BETA_SLACK)

    def _assemble(self, conn: _Conn, codec: int, batch: int, beta: float):
        """Sample + encode ONE batch (worker thread only — the memory is
        not thread-safe).  Raises ValueError while the memory is not yet
        sampleable.  Returns ``(codec, batch, beta, metas, buffers,
        nbytes, slot_off)``: when the connection carries a shm arena and a
        slot is free, the wire buffers are written ONCE into the slot at
        ``slot_off`` and ``buffers`` comes back empty (the control frame
        carries only metas); ``slot_off`` None means the bytes ride the
        frame blob as usual.  Either way the entry stays bit-stable
        however long it waits in the ring."""
        s = self.memory.sample(batch, beta)
        arrays = {
            "idx": s.idx + self.slot_base,  # wire ids are GLOBAL
            "obs": s.obs, "action": s.action, "reward": s.reward,
            "next_obs": s.next_obs, "discount": s.discount,
            "weight": s.weight,
        }
        if s.prob is not None:
            arrays["prob"] = s.prob
        if codec >= 2 and conn.arena is not None:
            # arena bytes never cross a network: skip the per-column
            # word-sums too (the control frame itself stays CRC-checked)
            metas, buffers = protocol.encode_batch_v2(arrays, sums=False)
            nbytes = sum(int(m["nbytes"]) for m in metas)
            with self._lock:
                arena = conn.arena
                if arena is not None:
                    if not arena.slot_bytes:
                        raw = sum(v.nbytes for v in
                                  map(np.asarray, arrays.values()))
                        arena.ensure_sized(raw)
                    off = arena.alloc(nbytes)
                else:
                    off = None
            if off is not None:
                arena.write(off, buffers)
                return (codec, batch, beta, metas, [], nbytes, off)
            # arena exhausted (client holding slots): blob fallback needs
            # the word-sums back on — these bytes DO cross the socket
            metas, buffers = protocol.encode_batch_v2(arrays)
            return (codec, batch, beta, metas, buffers, nbytes, None)
        if codec >= 2:
            metas, buffers = protocol.encode_batch_v2(arrays)
            nbytes = sum(int(m["nbytes"]) for m in metas)
        else:
            metas, buffers = protocol.encode_arrays_views(arrays)
            nbytes = sum(len(b) if isinstance(b, bytes) else b.nbytes
                         for b in buffers)
        return (codec, batch, beta, metas, buffers, nbytes, None)

    def _send_batches(self, conn: _Conn, rid: Any, codec: int,
                      entries: List[Any]) -> None:
        """Reply with pre-encoded batches: one frame, blob = the entries'
        wire buffers concatenated by the vectored writer (zero copies
        between the replay ring and the socket)."""
        if codec >= 2:
            # v2 columns carry their own word-sums, so the frame envelope
            # skips the blob CRC (the single largest CPU cost on the path)
            header = {"op": "batch", "rid": rid, "codec": 2,
                      "batches": [e[3] for e in entries]}
            if conn.arena is not None:
                # shm path: per-batch arena byte-offsets, null = that
                # batch's bytes ride the blob (arena was full)
                header["slots"] = [e[6] for e in entries]
            self._reply(conn, header,
                        [b for e in entries for b in e[4]],
                        crc_blob=False)
        else:
            self._reply(conn, {"op": "batch", "rid": rid,
                               "arrays": entries[0][3]},
                        list(entries[0][4]))

    def _ring_serve(self, conn: _Conn, rid: Any,
                    header: Dict[str, Any]) -> bool:
        """EVENT-LOOP fast path: answer a sample request entirely from the
        connection's pre-assembled ring — no work-queue wait behind
        appends, no memory access, no encode.  False (fall through to the
        worker) when the ring cannot cover the request."""
        if self.ring_depth <= 0:
            return False
        try:
            codec, n, batch, beta = self._negotiate(header)
        except (KeyError, TypeError, ValueError):
            return False  # malformed; let the worker path raise the rerr
        with self._lock:
            ring = conn.ring
            while ring and not self._entry_matches(ring[0], codec, batch,
                                                   beta):
                e = ring.popleft()  # stale shape/beta: worker rebuilds
                if e[6] is not None and conn.arena is not None:
                    conn.arena.release(e[6])
            if len(ring) < n:
                return False
            entries = [ring.popleft() for _ in range(n)]
            self.ring_hits += n
            self.samples_served += n
        self._send_batches(conn, rid, codec, entries)
        try:  # opportunistic top-up; a full work queue just skips it
            self._work.put_nowait((conn, "refill", None, None, None))
        except queue.Full:
            pass
        return True

    def _refill(self, conn: _Conn) -> None:
        """Top the connection's sample-ahead ring back up to
        ``ring_depth`` pre-encoded batches of its last request shape.
        Worker thread only.  Quietly stops while the memory is not
        sampleable or the connection is gone."""
        if self.ring_depth <= 0:
            return
        with self._lock:
            want = conn.ring_want
            need = self.ring_depth - len(conn.ring)
            gone = self._conns.get(_fd(conn)) is not conn
        if want is None or need <= 0 or gone:
            return
        codec, batch, beta = want
        entries = []
        for _ in range(need):
            try:
                entries.append(self._assemble(conn, codec, batch, beta))
            except ValueError:
                break  # not sampleable (yet): the next sample will retry
        if entries:
            with self._lock:
                conn.ring.extend(entries)

    def _do_sample(self, conn: _Conn, rid: Any,
                   header: Dict[str, Any]) -> None:
        codec, n, batch, beta = self._negotiate(header)
        with self._lock:
            conn.ring_want = (codec, batch, beta)
            ring = conn.ring
            while ring and not self._entry_matches(ring[0], codec, batch,
                                                   beta):
                e = ring.popleft()
                if e[6] is not None and conn.arena is not None:
                    conn.arena.release(e[6])
            entries = [ring.popleft()
                       for _ in range(min(n, len(ring)))]
            self.ring_hits += len(entries)
        try:
            while len(entries) < n:
                entries.append(self._assemble(conn, codec, batch, beta))
        except ValueError as e:  # all surviving shards empty: not yet warm
            if not entries:
                self._reply(conn, {"op": "rerr", "rid": rid,
                                   "etype": "empty", "msg": str(e)})
                return
        with self._lock:
            self.samples_served += len(entries)
        self._send_batches(conn, rid, codec, entries)
        # refill AFTER replying: the client decodes while we pre-assemble
        self._refill(conn)

    def _do_update(self, conn: _Conn, rid: Any, header: Dict[str, Any],
                   blob: bytes) -> None:
        if self._fenced(header):
            self.fenced_updates += 1
            self._reply(conn, {"op": "ack", "rid": rid, "ok": False,
                               "fenced": True})
            return
        if self._stale_learner(header):
            self.fenced_updates += 1
            self._reply(conn, {"op": "ack", "rid": rid, "ok": False,
                               "fenced": True, "stale_learner": True})
            return
        arrays = protocol.decode_arrays(header.get("arrays", ()), blob)
        self.memory.update_priorities(
            arrays["idx"] - self.slot_base,  # back to this block's ids
            arrays["td"])
        self.updates_applied += int(arrays["idx"].shape[0])
        self._reply(conn, {"op": "ack", "rid": rid, "ok": True})

    def _do_snapshot(self, conn: _Conn, rid: Any,
                     header: Dict[str, Any]) -> None:
        step = int(header.get("step", 0))
        if self.snapshot_prefix is None:
            self._reply(conn, {"op": "rerr", "rid": rid,
                               "etype": "unsupported",
                               "msg": "server has no snapshot prefix"})
            return
        if self._stale_learner(header):
            # a zombie's snapshot request must not overwrite the shard
            # block's on-disk state with its stale view — refused even when
            # its step counter ran AHEAD of the successor's (the step fence
            # below cannot catch that case; the epoch dimension can)
            self._reply(conn, {"op": "rerr", "rid": rid,
                               "etype": "stale_fence",
                               "msg": f"snapshot from superseded learner "
                                      f"epoch {header.get('learner_epoch')} "
                                      f"(latched {self.learner_epoch})"})
            return
        if step < self.snapshot_step:
            # the learner's checkpoint step is the fence: a replayed or
            # reordered request older than what is already on disk must not
            # roll the shard block backwards
            self._reply(conn, {"op": "rerr", "rid": rid,
                               "etype": "stale_fence",
                               "msg": f"snapshot step {step} older than "
                                      f"fenced step {self.snapshot_step}"})
            return
        self.memory.snapshot(self.snapshot_prefix)
        self.snapshot_step = step
        self._write_snapshot_step(step)
        self._log("snapshot", step=step, shard_base=self.shard_base)
        self._reply(conn, {"op": "ack", "rid": rid, "ok": True,
                           "step": step})

    # -------------------------------------------------------------- snapshots
    def _step_path(self) -> str:
        return f"{self.snapshot_prefix}_step"

    def _write_snapshot_step(self, step: int) -> None:
        tmp = self._step_path() + ".tmp"
        with open(tmp, "w") as f:
            f.write(str(int(step)))
        os.replace(tmp, self._step_path())

    def _learner_epoch_path(self) -> str:
        return f"{self.snapshot_prefix}_learner_epoch"

    def _write_learner_epoch(self, epoch: int) -> None:
        tmp = self._learner_epoch_path() + ".tmp"
        with open(tmp, "w") as f:
            f.write(str(int(epoch)))
        os.replace(tmp, self._learner_epoch_path())

    def _maybe_restore(self) -> None:
        """Restore this server's shard block from its own snapshot (the
        server-side resume path: the learner checkpoint carries no replay
        payload when the plane is on).  Missing/torn snapshots read as
        'cold start' — the epoch fence already guards the semantics."""
        try:
            # the learner-epoch latch restores INDEPENDENTLY of the replay
            # payload: a cold-started shard block must still refuse a
            # patient zombie's write-backs
            with open(self._learner_epoch_path()) as f:
                self.learner_epoch = int(f.read().strip() or -1)
        except (OSError, ValueError):
            pass
        try:
            self.memory.restore(self.snapshot_prefix)
        except FileNotFoundError:
            return
        except Exception as e:
            self._log("restore_failed", why=f"{type(e).__name__}: {e}")
            return
        try:
            with open(self._step_path()) as f:
                self.snapshot_step = int(f.read().strip() or -1)
        except (OSError, ValueError):
            self.snapshot_step = -1
        self._refresh_advisory()
        self._log("restored", step=self.snapshot_step,
                  rows=int(self._adv["size"]))

    # ---------------------------------------------------------------- stats
    def stats(self) -> Dict[str, Any]:
        with self._lock:
            n = len(self._conns)
            by_op = dict(self._bytes_by_op)
            ring = sum(len(c.ring) for c in self._conns.values())
            shm_conns = sum(1 for c in self._conns.values()
                            if c.arena is not None)
            shm_free = sum(len(c.arena.free) for c in self._conns.values()
                           if c.arena is not None)
            shm_total = sum(c.arena.total_slots
                            for c in self._conns.values()
                            if c.arena is not None)
        return {"port": self.port, "connections": n,
                "shm_conns": shm_conns, "shm_slots_free": shm_free,
                "shm_slots_total": shm_total,
                "frames_in": self.frames_in, "bytes_out": self.bytes_out,
                "bytes_by_op": by_op,
                "rows_appended": self.rows_appended,
                "fenced_appends": self.fenced_appends,
                "fenced_updates": self.fenced_updates,
                "samples_served": self.samples_served,
                "ring_hits": self.ring_hits,
                "ring_depth": ring,
                "wire": protocol.WIRE_CODEC_MAX,
                "updates_applied": self.updates_applied,
                "snapshot_step": self.snapshot_step,
                "learner_epoch": self.learner_epoch,
                "fenced_learner_writes": self.fenced_learner_writes}
