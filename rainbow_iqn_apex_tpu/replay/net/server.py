"""Server half of the cross-host replay plane: `ReplayShardServer` owns one
contiguous block of global replay shards (a `ShardedReplay` built for just
that block) and speaks the netcore frame protocol to N clients — actor-side
`AppendClient`s feeding transitions in, one learner-side `SampleClient`
draining assembled batches and writing priorities back.

Topology (the Ape-X/Redis-shard picture, now actually disaggregated): the
global replay of ``R`` shards is split into per-server blocks; a server
constructed with ``shard_base=b`` owning ``S`` local shards serves global
shards ``[b, b+S)`` and global slot ids ``[b*C, (b+S)*C)`` — it translates
at the wire boundary, so clients and the learner's `WritebackRing` see the
SAME global id space the in-process `ShardedReplay` exposes.

Concurrency: the selectors-driven event loop (the serving plane's
`TransportServer` shape — accepts + reads on one daemon thread, replies
drained by per-connection writer threads) never touches the replay memory.
ALL memory ops (append/sample/update/snapshot) funnel through ONE worker
thread via a bounded work queue — `ShardedReplay` is not thread-safe, and
serialising writers is exactly the single-redis-instance semantics each
shard block already models.  Pings and stats answer inline on the loop, so
liveness probes stay bounded behind a slow sample.

Fencing: the server carries the lease epoch its incarnation claimed
(``next_lease_epoch``); ``append``/``update`` frames stamped with an OLDER
epoch are acked ``fenced: true`` and dropped — a respawned server's
clients cannot resurrect a dead incarnation's spool into the revived shard
block.  Acks are sent AFTER the memory op lands (worker-thread ordering),
so an acked append is durably in the ring: the zero-loss gate the smoke
(scripts/replay_net_smoke.py) asserts counts exactly these.

Snapshots run server-side (``snapshot`` op), fenced by the learner's
checkpoint step: a replayed or reordered snapshot request older than the
last fenced step is refused, and a restarting server restores its own shard
block from its snapshot prefix before accepting traffic.

jax-free (numpy + netcore + replay host structures): a shard server is a
DRAM process, never a device one.
"""

from __future__ import annotations

import os
import queue
import selectors
import socket
import threading
from typing import Any, Dict, Optional

from rainbow_iqn_apex_tpu.netcore import chaos, framing
from rainbow_iqn_apex_tpu.replay.net import protocol

# bound on one reply write: a peer that stalls reading for this long is
# dropped (its requests settle as PeerDead client-side) instead of wedging
# the writing thread
_SEND_TIMEOUT_S = 5.0
# bound on queued memory ops: a client pipelining far past the worker's
# drain rate is backpressured by its own acks, so a full queue means a
# runaway peer — shed the op with a reasoned rerr instead of growing
_WORK_QUEUE_DEPTH = 256


class _Conn:
    """One accepted client connection: socket, incremental frame reader,
    and a bounded outbound queue drained by this connection's OWN writer
    thread (neither the selector loop nor the memory worker ever blocks on
    a peer's full send buffer)."""

    __slots__ = ("sock", "reader", "peer", "outq")

    def __init__(self, sock: socket.socket, max_frame_bytes: int):
        self.sock = sock
        self.reader = framing.FrameReader(max_frame_bytes)
        self.outq: "queue.Queue" = queue.Queue(maxsize=4096)
        try:
            self.peer = "%s:%s" % sock.getpeername()[:2]
        except OSError:
            self.peer = "?"


class ReplayShardServer:
    """Serve one shard block of the global replay over the framed protocol.

    ``memory`` is the `ShardedReplay` this server owns (its local shard 0 is
    global shard ``shard_base``); ``epoch`` is the lease epoch of this
    incarnation (stamp from ``next_lease_epoch`` in deployments — the write
    fence clients are checked against).  ``port=0`` binds an ephemeral port
    (read ``.port``); ``snapshot_prefix`` enables the server-side
    ``snapshot`` op and the restore-on-start path.
    """

    def __init__(self, memory: Any, shard_base: int = 0,
                 host: str = "127.0.0.1", port: int = 0,
                 advertise: Optional[str] = None,
                 max_frame_bytes: int = framing.DEFAULT_MAX_FRAME,
                 epoch: int = 0, snapshot_prefix: Optional[str] = None,
                 logger=None):
        self.memory = memory
        self.shard_base = int(shard_base)
        self.slot_base = self.shard_base * memory.shard_capacity
        self.epoch = int(epoch)
        self.snapshot_prefix = snapshot_prefix
        self.max_frame_bytes = int(max_frame_bytes)
        self.logger = logger
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, int(port)))
        self._listener.listen(64)
        self._listener.setblocking(False)
        self.port = self._listener.getsockname()[1]
        self.advertise = advertise or (
            "127.0.0.1" if host in ("", "0.0.0.0") else host)
        self._selector = selectors.DefaultSelector()
        self._selector.register(self._listener, selectors.EVENT_READ, None)
        self._conns: Dict[int, _Conn] = {}  # fd -> conn
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._worker: Optional[threading.Thread] = None
        self._work: "queue.Queue" = queue.Queue(maxsize=_WORK_QUEUE_DEPTH)
        # lifetime counters (the smoke's gates + the stats op)
        self.frames_in = 0
        self.bytes_out = 0
        self.rows_appended = 0  # acked-and-landed transition rows
        self.fenced_appends = 0
        self.fenced_updates = 0
        self.samples_served = 0
        self.updates_applied = 0
        self.snapshot_step = -1
        # learner-role epoch latch (parallel/failover.py): priority
        # write-backs and snapshot requests stamped by a SUPERSEDED learner
        # incarnation are refused — the step fence below grown an epoch
        # dimension.  -1 = no failover-armed learner ever wrote; unstamped
        # frames (every pre-failover client) always pass, so the off path
        # is bitwise intact.  Persisted beside the snapshot step so a
        # restarted server cannot be rolled back by a patient zombie.
        self.learner_epoch = -1
        self.fenced_learner_writes = 0
        # advisory piggyback state: written by the worker after each memory
        # op, read (under the lock) by every reply — the event loop never
        # touches the un-thread-safe memory itself
        self._adv: Dict[str, Any] = {}
        # live fleet telemetry (obs/net/): from_config attaches a relay so
        # a disaggregated replay host shows up on the fleet dashboard like
        # every other role; None on the default path and direct constructs
        self.obs_relay = None
        self._refresh_advisory()
        if snapshot_prefix is not None:
            self._maybe_restore()

    @classmethod
    def from_config(cls, cfg, memory: Any, epoch: int = 0,
                    snapshot_prefix: Optional[str] = None,
                    logger=None) -> Optional["ReplayShardServer"]:
        """The config seam: ``replay_net_host`` unset (default) returns None
        — replay stays in-process, bitwise the pre-net path."""
        if not getattr(cfg, "replay_net_host", ""):
            return None
        srv = cls(
            memory, shard_base=int(cfg.replay_net_shard_base),
            host=cfg.replay_net_host, port=cfg.replay_net_port,
            advertise=cfg.replay_net_advertise or None,
            max_frame_bytes=int(cfg.replay_net_max_frame_mb) << 20,
            epoch=epoch, snapshot_prefix=snapshot_prefix, logger=logger)
        if logger is not None and getattr(cfg, "obs_net", False):
            from rainbow_iqn_apex_tpu.obs.net.relay import ObsRelay

            srv.obs_relay = ObsRelay.attach(cfg, logger, role="replay_shard")
        return srv

    def attach_lease(self, writer) -> None:
        """Advertise ``addr:port`` (and the shard block) in this server's
        lease payload so clients discover the endpoint through the lease
        files they already watch — no second discovery protocol.  Call
        BEFORE ``writer.start()`` so the very first beat carries it."""
        writer.update_payload(addr=self.advertise, port=self.port,
                              shard_base=self.shard_base,
                              shards=len(self.memory.shards))

    # -------------------------------------------------------------- lifecycle
    def start(self) -> "ReplayShardServer":
        if self._thread is None:
            self._worker = threading.Thread(
                target=self._work_loop, name=f"replaynet-mem-{self.port}",
                daemon=True)
            self._worker.start()
            self._thread = threading.Thread(
                target=self._run, name=f"replaynet-server-{self.port}",
                daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        """Close the listener and every connection.  Clients see the drop
        as `PeerDead` and re-route to survivors — the wire analog of
        ``drop_shard``."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        if self._worker is not None:
            try:
                self._work.put_nowait(None)
            except queue.Full:
                pass
            self._worker.join(timeout=10)
            self._worker = None
        with self._lock:
            conns = list(self._conns.values())
            self._conns.clear()
        for conn in conns:
            self._close_conn(conn, unregister=False)
        try:
            self._selector.close()
        except (OSError, RuntimeError):
            pass
        try:
            self._listener.close()
        except OSError:
            pass
        if self.obs_relay is not None:
            self.obs_relay.close()
            self.obs_relay = None

    # -------------------------------------------------------------- event loop
    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                events = self._selector.select(timeout=0.1)
            except OSError:
                return
            for key, _mask in events:
                if key.fileobj is self._listener:
                    self._accept()
                else:
                    self._read(key.data)

    def _accept(self) -> None:
        try:
            sock, _addr = self._listener.accept()
        except OSError:
            return
        # blocking with a bound (see TransportServer._accept): sendall
        # loops through partial writes; only a peer stalled past the bound
        # is dropped.  Reads stay selector-driven.
        sock.settimeout(_SEND_TIMEOUT_S)
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass
        sock = chaos.maybe_wrap(sock, peer=f"{_addr[0]}:{_addr[1]}",
                                logger=self.logger)
        conn = _Conn(sock, self.max_frame_bytes)
        with self._lock:
            self._conns[sock.fileno()] = conn
        threading.Thread(target=self._write_loop, args=(conn,),
                         name=f"replaynet-writer-{self.port}",
                         daemon=True).start()
        self._selector.register(sock, selectors.EVENT_READ, conn)

    def _close_conn(self, conn: _Conn, unregister: bool = True) -> None:
        if unregister:
            try:
                self._selector.unregister(conn.sock)
            except (KeyError, OSError, ValueError):
                pass
            with self._lock:
                self._conns.pop(conn.sock.fileno(), None)
        try:
            conn.outq.put_nowait(None)  # stop the writer thread
        except queue.Full:
            pass  # writer will exit on the closed socket's send error
        try:
            conn.sock.close()
        except OSError:
            pass

    def _read(self, conn: _Conn) -> None:
        try:
            data = conn.sock.recv(1 << 16)
        except (BlockingIOError, socket.timeout):
            return  # spurious readiness; nothing to read this round
        except OSError:
            self._close_conn(conn)
            return
        if not data:
            self._close_conn(conn)
            return
        try:
            frames = conn.reader.feed(data)
        except framing.FrameError as e:
            # torn/corrupt/oversize append frame: the CRC trailer caught it
            # BEFORE any rows landed — drop the connection with one
            # reasoned row; the client's spool re-ships after reconnect
            # (docs/RESILIENCE.md, "torn append frame")
            self._log("bad_frame", peer=conn.peer,
                      why=f"{type(e).__name__}: {e}")
            self._close_conn(conn)
            return
        for header, blob in frames:
            self.frames_in += 1
            try:
                self._handle(conn, header, blob)
            except Exception as e:
                self._reply(conn, {"op": "rerr",
                                   "rid": header.get("rid"),
                                   "etype": "dead",
                                   "msg": f"{type(e).__name__}: {e}"})

    # ---------------------------------------------------------------- replies
    def _log(self, event: str, **fields: Any) -> None:
        if self.logger is not None:
            try:
                self.logger.log("replay_net", event=event, **fields)
            except Exception:
                pass

    def _refresh_advisory(self) -> None:
        """Recompute the piggyback state from the memory.  WORKER-thread
        only (plus construction, before any thread exists) — replies read
        the cached copy under the lock."""
        mem = self.memory
        alive = [s for k, s in enumerate(mem.shards)
                 if k not in mem._dead]  # advisory read; worker-serialised
        adv = {
            "size": sum(len(s) for s in alive),
            "sampleable": bool(mem.sampleable),
            "mass": float(sum(s.tree.total for s in alive)),
            "epoch": self.epoch,
            "shard_base": self.shard_base,
            "shards": len(mem.shards),
            "capacity": int(mem.shard_capacity),
        }
        with self._lock:
            self._adv = adv

    def _state(self) -> Dict[str, Any]:
        with self._lock:
            return dict(self._adv)

    def _reply(self, conn: _Conn, header: Dict[str, Any],
               blob: bytes = b"") -> None:
        """Enqueue one reply for the connection's writer thread (the event
        loop and the memory worker never touch the socket).  A full queue
        means the peer is long stalled — drop it instead of growing."""
        header = {**header, **self._state()}
        try:
            conn.outq.put_nowait((header, blob))
        except queue.Full:
            self._close_conn(conn)

    def _write_loop(self, conn: _Conn) -> None:
        while True:
            item = conn.outq.get()
            if item is None:  # close sentinel
                return
            header, blob = item
            try:
                self.bytes_out += framing.send_frame(conn.sock, header, blob)
            except (OSError, ValueError):
                self._close_conn(conn)
                return

    # ---------------------------------------------------------------- handlers
    def _handle(self, conn: _Conn, header: Dict[str, Any],
                blob: bytes) -> None:
        op = header.get("op")
        rid = header.get("rid")
        if op == "ping":
            self._reply(conn, {"op": "pong", "rid": rid, "alive": True})
        elif op == "stats":
            self._reply(conn, {"op": "stats_reply", "rid": rid,
                               **self.stats()})
        elif op in ("append", "sample", "update", "snapshot"):
            # memory ops run on the ONE worker thread; the bounded queue
            # sheds a runaway pipeliner with a reasoned rerr instead of
            # buffering without bound
            try:
                self._work.put_nowait((conn, op, rid, header, blob))
            except queue.Full:
                self._reply(conn, {"op": "rerr", "rid": rid,
                                   "etype": "unsupported",
                                   "msg": "server work queue full (client "
                                          "pipelining past the drain rate)"})
        else:
            self._reply(conn, {"op": "rerr", "rid": rid,
                               "etype": "unsupported",
                               "msg": f"unknown op {op!r}"})

    def _work_loop(self) -> None:
        while True:
            item = self._work.get()
            if item is None:
                return
            conn, op, rid, header, blob = item
            try:
                if op == "append":
                    self._do_append(conn, rid, header, blob)
                elif op == "sample":
                    self._do_sample(conn, rid, header)
                elif op == "update":
                    self._do_update(conn, rid, header, blob)
                else:
                    self._do_snapshot(conn, rid, header)
                self._refresh_advisory()
            except Exception as e:
                self._reply(conn, {"op": "rerr", "rid": rid,
                                   "etype": "dead",
                                   "msg": f"{type(e).__name__}: {e}"})

    def _fenced(self, header: Dict[str, Any]) -> bool:
        """True when the frame's epoch stamp names a STALE incarnation of
        this shard block (the respawned-server split-brain fence).  A frame
        with no epoch — a client that has not learned one yet — passes, the
        same ``epoch=None`` contract `ShardedReplay._fence` keeps."""
        epoch = header.get("epoch")
        return epoch is not None and int(epoch) != self.epoch

    def _stale_learner(self, header: Dict[str, Any]) -> bool:
        """True when the frame's ``learner_epoch`` stamp names a SUPERSEDED
        learner incarnation (the zombie fence — docs/RESILIENCE.md "zombie
        learner").  Unstamped frames pass; a NEWER stamp latches (and
        persists) the new floor, so once the successor's first write lands
        the predecessor is refused forever, restarts included."""
        le = header.get("learner_epoch")
        if le is None:
            return False
        le = int(le)
        if le < self.learner_epoch:
            self.fenced_learner_writes += 1
            self._log("stale_learner", learner_epoch=le,
                      latched=self.learner_epoch)
            return True
        if le > self.learner_epoch:
            self.learner_epoch = le
            if self.snapshot_prefix is not None:
                self._write_learner_epoch(le)
        return False

    def _do_append(self, conn: _Conn, rid: Any, header: Dict[str, Any],
                   blob: bytes) -> None:
        if self._fenced(header):
            self.fenced_appends += 1
            self._reply(conn, {"op": "ack", "rid": rid, "ok": False,
                               "fenced": True})
            return
        arrays = protocol.decode_arrays(header.get("arrays", ()), blob)
        frames, actions = arrays["frames"], arrays["actions"]
        ticks = int(header.get("ticks", 1))
        if ticks <= 0 or actions.shape[0] != ticks:
            raise ValueError(
                f"append block declares {ticks} ticks, arrays carry "
                f"{actions.shape[0]}")
        pri = arrays.get("priorities")
        trunc = arrays.get("truncations")
        rows = 0
        for t in range(ticks):
            # each tick is one lockstep lane append: ring order inside the
            # block is exactly the order the producer experienced
            self.memory.append_batch(
                frames[t], actions[t], arrays["rewards"][t],
                arrays["terminals"][t],
                None if pri is None else pri[t],
                None if trunc is None else trunc[t])
            rows += int(actions[t].shape[0])
        self.rows_appended += rows
        self._reply(conn, {"op": "ack", "rid": rid, "ok": True,
                           "rows": rows})

    def _do_sample(self, conn: _Conn, rid: Any,
                   header: Dict[str, Any]) -> None:
        try:
            s = self.memory.sample(int(header["batch"]),
                                   float(header["beta"]))
        except ValueError as e:  # all surviving shards empty: not yet warm
            self._reply(conn, {"op": "rerr", "rid": rid, "etype": "empty",
                               "msg": str(e)})
            return
        self.samples_served += 1
        arrays = {
            "idx": s.idx + self.slot_base,  # wire ids are GLOBAL
            "obs": s.obs, "action": s.action, "reward": s.reward,
            "next_obs": s.next_obs, "discount": s.discount,
            "weight": s.weight,
        }
        if s.prob is not None:
            arrays["prob"] = s.prob
        metas, payload = protocol.encode_arrays(arrays)
        self._reply(conn, {"op": "batch", "rid": rid, "arrays": metas},
                    payload)

    def _do_update(self, conn: _Conn, rid: Any, header: Dict[str, Any],
                   blob: bytes) -> None:
        if self._fenced(header):
            self.fenced_updates += 1
            self._reply(conn, {"op": "ack", "rid": rid, "ok": False,
                               "fenced": True})
            return
        if self._stale_learner(header):
            self.fenced_updates += 1
            self._reply(conn, {"op": "ack", "rid": rid, "ok": False,
                               "fenced": True, "stale_learner": True})
            return
        arrays = protocol.decode_arrays(header.get("arrays", ()), blob)
        self.memory.update_priorities(
            arrays["idx"] - self.slot_base,  # back to this block's ids
            arrays["td"])
        self.updates_applied += int(arrays["idx"].shape[0])
        self._reply(conn, {"op": "ack", "rid": rid, "ok": True})

    def _do_snapshot(self, conn: _Conn, rid: Any,
                     header: Dict[str, Any]) -> None:
        step = int(header.get("step", 0))
        if self.snapshot_prefix is None:
            self._reply(conn, {"op": "rerr", "rid": rid,
                               "etype": "unsupported",
                               "msg": "server has no snapshot prefix"})
            return
        if self._stale_learner(header):
            # a zombie's snapshot request must not overwrite the shard
            # block's on-disk state with its stale view — refused even when
            # its step counter ran AHEAD of the successor's (the step fence
            # below cannot catch that case; the epoch dimension can)
            self._reply(conn, {"op": "rerr", "rid": rid,
                               "etype": "stale_fence",
                               "msg": f"snapshot from superseded learner "
                                      f"epoch {header.get('learner_epoch')} "
                                      f"(latched {self.learner_epoch})"})
            return
        if step < self.snapshot_step:
            # the learner's checkpoint step is the fence: a replayed or
            # reordered request older than what is already on disk must not
            # roll the shard block backwards
            self._reply(conn, {"op": "rerr", "rid": rid,
                               "etype": "stale_fence",
                               "msg": f"snapshot step {step} older than "
                                      f"fenced step {self.snapshot_step}"})
            return
        self.memory.snapshot(self.snapshot_prefix)
        self.snapshot_step = step
        self._write_snapshot_step(step)
        self._log("snapshot", step=step, shard_base=self.shard_base)
        self._reply(conn, {"op": "ack", "rid": rid, "ok": True,
                           "step": step})

    # -------------------------------------------------------------- snapshots
    def _step_path(self) -> str:
        return f"{self.snapshot_prefix}_step"

    def _write_snapshot_step(self, step: int) -> None:
        tmp = self._step_path() + ".tmp"
        with open(tmp, "w") as f:
            f.write(str(int(step)))
        os.replace(tmp, self._step_path())

    def _learner_epoch_path(self) -> str:
        return f"{self.snapshot_prefix}_learner_epoch"

    def _write_learner_epoch(self, epoch: int) -> None:
        tmp = self._learner_epoch_path() + ".tmp"
        with open(tmp, "w") as f:
            f.write(str(int(epoch)))
        os.replace(tmp, self._learner_epoch_path())

    def _maybe_restore(self) -> None:
        """Restore this server's shard block from its own snapshot (the
        server-side resume path: the learner checkpoint carries no replay
        payload when the plane is on).  Missing/torn snapshots read as
        'cold start' — the epoch fence already guards the semantics."""
        try:
            # the learner-epoch latch restores INDEPENDENTLY of the replay
            # payload: a cold-started shard block must still refuse a
            # patient zombie's write-backs
            with open(self._learner_epoch_path()) as f:
                self.learner_epoch = int(f.read().strip() or -1)
        except (OSError, ValueError):
            pass
        try:
            self.memory.restore(self.snapshot_prefix)
        except FileNotFoundError:
            return
        except Exception as e:
            self._log("restore_failed", why=f"{type(e).__name__}: {e}")
            return
        try:
            with open(self._step_path()) as f:
                self.snapshot_step = int(f.read().strip() or -1)
        except (OSError, ValueError):
            self.snapshot_step = -1
        self._refresh_advisory()
        self._log("restored", step=self.snapshot_step,
                  rows=int(self._adv["size"]))

    # ---------------------------------------------------------------- stats
    def stats(self) -> Dict[str, Any]:
        with self._lock:
            n = len(self._conns)
        return {"port": self.port, "connections": n,
                "frames_in": self.frames_in, "bytes_out": self.bytes_out,
                "rows_appended": self.rows_appended,
                "fenced_appends": self.fenced_appends,
                "fenced_updates": self.fenced_updates,
                "samples_served": self.samples_served,
                "updates_applied": self.updates_applied,
                "snapshot_step": self.snapshot_step,
                "learner_epoch": self.learner_epoch,
                "fenced_learner_writes": self.fenced_learner_writes}
