"""Atomic, CRC-verified npz snapshot IO shared by the replay implementations.

A snapshot exists to survive kills (resume support), so the write itself
must survive kills: np.savez straight onto the destination truncates the
previous good snapshot before the new one is complete, and a SIGKILL
mid-write leaves nothing restorable.  Writes here go to a temp file in the
same directory followed by os.replace (atomic on POSIX), so the destination
always holds either the old snapshot or the new one — never a torn file.

Atomicity protects against OUR kills; it cannot protect against a torn
write below the rename (network FS replaying a partial flush, disk
corruption, a copy truncated in flight).  Every snapshot therefore carries
a CRC32 over its payload arrays (``__crc32__`` entry), verified EAGERLY at
``load()`` — zipfile's per-entry CRCs only fire lazily at array access,
which for a replay restore would mean dying mid-restore with the buffer
half-overwritten.  A failed check raises ``SnapshotCorrupt``, which is part
of ``MISSING``: restore paths treat a corrupt snapshot exactly like an
absent one (cold replay) instead of crashing the run.
"""

from __future__ import annotations

import os
import zipfile
import zlib

import numpy as np

from rainbow_iqn_apex_tpu.utils import faults


class SnapshotCorrupt(Exception):
    """Snapshot payload does not match its recorded CRC32."""


# Exceptions that mean "no usable snapshot here" (missing, torn file from a
# kill, or payload corruption caught by the CRC), as opposed to caller
# errors like shape mismatch.
MISSING = (FileNotFoundError, zipfile.BadZipFile, EOFError, SnapshotCorrupt)

_CRC_KEY = "__crc32__"


def npz_path(path: str) -> str:
    """np.savez auto-appends .npz when given a filename; mirror that so
    save and load agree on the real destination."""
    return path if path.endswith(".npz") else path + ".npz"


def _payload_crc(arrays: dict) -> int:
    """CRC32 over names + raw bytes of every payload array, in sorted name
    order (layout-independent: the same logical contents always hash the
    same, whatever order the caller passed them in)."""
    crc = 0
    for name in sorted(arrays):
        if name == _CRC_KEY:
            continue
        arr = np.ascontiguousarray(np.asarray(arrays[name]))
        crc = zlib.crc32(name.encode(), crc)
        crc = zlib.crc32(arr.tobytes(), crc)
    return crc & 0xFFFFFFFF


def atomic_savez(path: str, **arrays) -> None:
    """Uncompressed atomic write (uint8 frames are near-incompressible and
    zlib would multiply the time any caller-held lock is taken)."""
    dest = npz_path(path)
    tmp = dest + ".tmp"
    arrays[_CRC_KEY] = np.uint32(_payload_crc(arrays))
    with open(tmp, "wb") as f:
        np.savez(f, **arrays)
    if faults.get().fire("replay_snapshot_corrupt"):
        # chaos: tear the file below the atomic rename (what a mid-flush
        # host loss or disk corruption produces) — the CRC must catch it
        size = os.path.getsize(tmp)
        with open(tmp, "r+b") as f:
            f.truncate(max(size // 2, 1))
    os.replace(tmp, dest)


class _Verified(dict):
    """Eagerly-materialised snapshot payload with the NpzFile ``files``
    attribute callers already use (`"cuts" in z.files`)."""

    @property
    def files(self):
        return list(self.keys())


def load(path: str, verify: bool = True):
    """np.load of a snapshot; raises one of MISSING when absent/torn/corrupt.

    Verification is eager: the whole payload is read and checked against
    the stored CRC before anything is returned, so a restore either starts
    from a proven-whole snapshot or not at all.  Pre-CRC-era snapshots
    (no ``__crc32__`` entry) pass through unverified.
    """
    z = np.load(npz_path(path))
    if not verify or _CRC_KEY not in z.files:
        return z
    try:
        arrays = {name: z[name] for name in z.files if name != _CRC_KEY}
        stored = int(z[_CRC_KEY])
    except (zipfile.BadZipFile, zlib.error, ValueError, OSError) as e:
        # a torn entry surfaces while eagerly materialising the payload
        raise SnapshotCorrupt(f"{npz_path(path)}: unreadable payload: {e}") from e
    actual = _payload_crc(arrays)
    if actual != stored:
        raise SnapshotCorrupt(
            f"{npz_path(path)}: crc32 {actual:#010x} != recorded {stored:#010x}"
        )
    return _Verified(arrays)
