"""Atomic npz snapshot IO shared by the replay implementations.

A snapshot exists to survive kills (resume support), so the write itself
must survive kills: np.savez straight onto the destination truncates the
previous good snapshot before the new one is complete, and a SIGKILL
mid-write leaves nothing restorable.  Writes here go to a temp file in the
same directory followed by os.replace (atomic on POSIX), so the destination
always holds either the old snapshot or the new one — never a torn file.
"""

from __future__ import annotations

import os
import zipfile

import numpy as np

# Exceptions that mean "no usable snapshot here" (missing or torn file from
# a pre-atomic-write kill), as opposed to caller errors like shape mismatch.
MISSING = (FileNotFoundError, zipfile.BadZipFile, EOFError)


def npz_path(path: str) -> str:
    """np.savez auto-appends .npz when given a filename; mirror that so
    save and load agree on the real destination."""
    return path if path.endswith(".npz") else path + ".npz"


def atomic_savez(path: str, **arrays) -> None:
    """Uncompressed atomic write (uint8 frames are near-incompressible and
    zlib would multiply the time any caller-held lock is taken)."""
    dest = npz_path(path)
    tmp = dest + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **arrays)
    os.replace(tmp, dest)


def load(path: str):
    """np.load of a snapshot; raises one of MISSING when absent/torn."""
    return np.load(npz_path(path))
