"""Atari-57 benchmark harness: game list, normalisation baselines, sweep
driver, and the median human-normalized aggregate.

Parity: the reference's headline benchmark is the 200M-frame median
human-normalized score over the 57-game ALE suite under SABER
(BASELINE.json:2, SURVEY.md §6), with per-game result CSVs shipped in the
repo (SURVEY.md §2 row 9).

The random/human baseline table below is the standard one from the
Rainbow/IQN literature (Wang et al. / Hessel et al. appendices).  Values are
from training-data recall and carry the survey's RECON caveat (SURVEY.md §0):
re-verify against the published appendix before using in a paper.  The
aggregation math (score normalisation, median) does not depend on their
exactness.
"""

from __future__ import annotations

import csv
import json
import os
from typing import Dict, List, Optional

# game -> (random, human) raw-score baselines [RECON — re-verify]
ATARI57_BASELINES: Dict[str, tuple] = {
    "Alien": (227.8, 7127.7), "Amidar": (5.8, 1719.5),
    "Assault": (222.4, 742.0), "Asterix": (210.0, 8503.3),
    "Asteroids": (719.1, 47388.7), "Atlantis": (12850.0, 29028.1),
    "BankHeist": (14.2, 753.1), "BattleZone": (2360.0, 37187.5),
    "BeamRider": (363.9, 16926.5), "Berzerk": (123.7, 2630.4),
    "Bowling": (23.1, 160.7), "Boxing": (0.1, 12.1),
    "Breakout": (1.7, 30.5), "Centipede": (2090.9, 12017.0),
    "ChopperCommand": (811.0, 7387.8), "CrazyClimber": (10780.5, 35829.4),
    "Defender": (2874.5, 18688.9), "DemonAttack": (152.1, 1971.0),
    "DoubleDunk": (-18.6, -16.4), "Enduro": (0.0, 860.5),
    "FishingDerby": (-91.7, -38.7), "Freeway": (0.0, 29.6),
    "Frostbite": (65.2, 4334.7), "Gopher": (257.6, 2412.5),
    "Gravitar": (173.0, 3351.4), "Hero": (1027.0, 30826.4),
    "IceHockey": (-11.2, 0.9), "Jamesbond": (29.0, 302.8),
    "Kangaroo": (52.0, 3035.0), "Krull": (1598.0, 2665.5),
    "KungFuMaster": (258.5, 22736.3), "MontezumaRevenge": (0.0, 4753.3),
    "MsPacman": (307.3, 6951.6), "NameThisGame": (2292.3, 8049.0),
    "Phoenix": (761.4, 7242.6), "Pitfall": (-229.4, 6463.7),
    "Pong": (-20.7, 14.6), "PrivateEye": (24.9, 69571.3),
    "Qbert": (163.9, 13455.0), "Riverraid": (1338.5, 17118.0),
    "RoadRunner": (11.5, 7845.0), "Robotank": (2.2, 11.9),
    "Seaquest": (68.4, 42054.7), "Skiing": (-17098.1, -4336.9),
    "Solaris": (1236.3, 12326.7), "SpaceInvaders": (148.0, 1668.7),
    "StarGunner": (664.0, 10250.0), "Surround": (-10.0, 6.5),
    "Tennis": (-23.8, -8.3), "TimePilot": (3568.0, 5229.2),
    "Tutankham": (11.4, 167.6), "UpNDown": (533.4, 11693.2),
    "Venture": (0.0, 1187.5), "VideoPinball": (16256.9, 17667.9),
    "WizardOfWor": (563.5, 4756.5), "YarsRevenge": (3092.9, 54576.9),
    "Zaxxon": (32.5, 9173.3),
}

ATARI57 = sorted(ATARI57_BASELINES)

# Registered human world records per game — the SABER protocol's headline
# normalisation (arXiv:1908.04683 reports world-record-normalised scores; its
# thesis is that "superhuman" agents reach only a small fraction of these).
# PARTIAL table [RECON — re-verify against the SABER appendix]: entries are
# included only where training-data recall is reasonably confident; the
# aggregation skips games without a record entry, reports coverage, and by
# default EXCLUDES unverified (RECON) entries from the headline number —
# load a vetted table with ``load_record_table`` to mark entries verified.
HUMAN_WORLD_RECORDS: Dict[str, float] = {
    "Asteroids": 10_004_100.0,
    "Atlantis": 10_604_840.0,
    "Breakout": 864.0,
    "Centipede": 1_301_709.0,
    "DonkeyKong": 1_218_000.0,  # not in the 57-set; harmless extra
    "MsPacman": 290_090.0,
    "Pong": 21.0,
    "Qbert": 2_400_000.0,
    "Seaquest": 999_999.0,
    "SpaceInvaders": 621_535.0,
    "VideoPinball": 89_218_328.0,
}

# Provenance per record entry: "recon" (training-data recall, unverified) or
# "verified" (injected from a vetted JSON table).  Nothing ships verified —
# the sandbox has no egress to check a source.
RECORD_PROVENANCE: Dict[str, str] = {g: "recon" for g in HUMAN_WORLD_RECORDS}


def load_record_table(path: str, verified: bool = True) -> int:
    """Merge a JSON world-record table into the in-process one.

    Accepts either ``{"Pong": 21.0, ...}`` or
    ``{"Pong": {"record": 21.0, "verified": true}, ...}``.  Entries loaded
    with ``verified`` (the default, overridable per entry) count toward the
    headline SABER aggregate; returns the number of entries merged.
    """
    with open(path) as f:
        table = json.load(f)
    n = 0
    for game, entry in table.items():
        if isinstance(entry, dict):
            value = float(entry["record"])
            is_verified = bool(entry.get("verified", verified))
        else:
            value = float(entry)
            is_verified = verified
        HUMAN_WORLD_RECORDS[game] = value
        RECORD_PROVENANCE[game] = "verified" if is_verified else "recon"
        n += 1
    return n


def record_is_verified(game: str) -> bool:
    return RECORD_PROVENANCE.get(game) == "verified"


def world_record_normalized(game: str, raw: float) -> Optional[float]:
    """(score - random) / (record - random), the SABER headline metric."""
    base = ATARI57_BASELINES.get(game)
    record = HUMAN_WORLD_RECORDS.get(game)
    if base is None or record is None or record == base[0]:
        return None
    return (raw - base[0]) / (record - base[0])


def human_normalized_score(game: str, raw: float) -> Optional[float]:
    base = ATARI57_BASELINES.get(game)
    if base is None or base[1] == base[0]:
        return None
    return (raw - base[0]) / (base[1] - base[0])


from statistics import median as _median  # noqa: E402


def aggregate(
    per_game_raw: Dict[str, float], include_recon_records: bool = False
) -> Dict[str, float]:
    """Median/mean human- and world-record-normalized over evaluated games.

    The headline ``median_world_record_normalized`` uses only VERIFIED record
    entries unless ``include_recon_records=True``; the RECON-inclusive value
    is always reported separately (suffix ``_recon``) with both coverage
    counts, so unvetted constants can never silently become the headline.
    """
    hns = [
        hn
        for g, s in per_game_raw.items()
        if (hn := human_normalized_score(g, s)) is not None
    ]
    if not hns:
        return {"games": 0}
    out = {
        "games": len(hns),
        "median_human_normalized": _median(hns),
        "mean_human_normalized": sum(hns) / len(hns),
    }
    wrs_all: Dict[str, float] = {
        g: wr
        for g, s in per_game_raw.items()
        if (wr := world_record_normalized(g, s)) is not None
    }
    wrs_verified = {g: wr for g, wr in wrs_all.items() if record_is_verified(g)}
    headline = wrs_all if include_recon_records else wrs_verified
    if headline:  # SABER metric over the covered subset
        out["median_world_record_normalized"] = _median(headline.values())
    if wrs_all:
        out["median_world_record_normalized_recon"] = _median(wrs_all.values())
    out["world_record_coverage_verified"] = len(wrs_verified)
    out["world_record_coverage_recon"] = len(wrs_all) - len(wrs_verified)
    return out


def write_results_csv(path: str, rows: List[Dict]) -> None:
    """Per-game results CSV (parity: the reference ships per-game CSVs)."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    fields = sorted({k for r in rows for k in r})
    with open(path, "w", newline="") as f:
        w = csv.DictWriter(f, fieldnames=fields)
        w.writeheader()
        w.writerows(rows)


# Env vars stashed/restored by the sweep's parent-CPU discipline (see
# sanitize_sweep_parent_env).  Everything the axon relay hook or an explicit
# device pin rides on.
_DEVICE_ENV_VARS = (
    "JAX_PLATFORMS",
    "PALLAS_AXON_POOL_IPS",
    "PALLAS_AXON_REMOTE_COMPILE",
    "PALLAS_AXON_TPU_GEN",
    "AXON_LOOPBACK_RELAY",
)
_DEVICE_ENV_STASH = "JAXSUITE_DEVICE_ENV"
_SANITIZED_FLAG = "JAXSUITE_PARENT_SANITIZED"


def sanitize_sweep_parent_env() -> None:
    """Re-exec the sweep parent pinned to CPU, stashing the device env.

    Against the single-claim TPU relay, a device backend initialized in the
    long-lived sweep parent holds the claim for the parent's whole life and
    starves every trainer child (observed 2026-07-31, first on-chip sweep
    attempt).  Call this BEFORE anything imports jax.  No-op when there is
    no device signal (plain CPU box) or after the re-exec.
    """
    import sys

    if os.environ.get(_SANITIZED_FLAG) == "1":
        return
    deviceish = bool(os.environ.get("PALLAS_AXON_POOL_IPS")) or \
        os.environ.get("JAX_PLATFORMS", "") not in ("", "cpu")
    if not deviceish:
        return
    stash = {k: os.environ[k] for k in _DEVICE_ENV_VARS if k in os.environ}
    if "JAX_PLATFORMS" not in stash and os.environ.get("PALLAS_AXON_POOL_IPS"):
        # pin children to the relay's platform: an unpinned child whose
        # backend init hits a relay blip SILENTLY falls back to CPU and
        # crawls for hours (observed 2026-07-31); a pinned child fails fast
        # with UNAVAILABLE and the sweep records an honest error/salvage row
        stash["JAX_PLATFORMS"] = "axon"
    env = dict(os.environ)
    env[_DEVICE_ENV_STASH] = json.dumps(stash)
    env[_SANITIZED_FLAG] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    os.execve(sys.executable, [sys.executable, *sys.argv], env)


def child_device_env() -> Dict[str, str]:
    """Env for a trainer child: the parent's env with the stashed device
    vars restored (so children claim the device one at a time) — or the
    plain env when no stash exists."""
    env = dict(os.environ)
    stash = env.pop(_DEVICE_ENV_STASH, None)
    env.pop(_SANITIZED_FLAG, None)
    if stash:
        restored = json.loads(stash)
        for k in _DEVICE_ENV_VARS:
            env.pop(k, None)
        env.update(restored)
    return env


def train_one_game(env_id: str, run_id: str, base_args: List[str]) -> Dict:
    """Train+eval one game via the training CLI (cwd-independent); returns
    the CLI's final JSON summary, or {} if none was printed.  Shared by this
    sweep and jaxsuite.run_sweep so orchestration can't drift."""
    import subprocess
    import sys

    train_cli = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "train_agent_apex.py",
    )
    cmd = [
        sys.executable, train_cli,
        "--env-id", env_id, "--run-id", run_id, *base_args,
    ]
    out = subprocess.run(cmd, capture_output=True, text=True,
                         env=child_device_env())
    if out.returncode != 0:
        tail = "\n".join(out.stderr.strip().splitlines()[-10:])
        print(
            f"[sweep] {env_id} training CLI failed (rc={out.returncode}):\n{tail}",
            file=sys.stderr,
        )
    for line in reversed(out.stdout.strip().splitlines()):
        try:
            return json.loads(line)
        except (ValueError, json.JSONDecodeError):
            continue
    return {}


def run_sweep(base_args: List[str], games: Optional[List[str]] = None,
              results_dir: str = "results/atari57",
              record_table: Optional[str] = None,
              include_recon_records: bool = False) -> Dict[str, float]:
    """Sequentially train+eval each game via the training CLI.

    One game at a time on one host's slice; pod-scale sweeps launch one game
    per slice with scripts/launch_apex.sh.  ``record_table`` loads a vetted
    world-record JSON before aggregating (see ``load_record_table``).
    Returns the aggregate, including verified/recon coverage counts.
    """
    if record_table:
        load_record_table(record_table)
    games = games or ATARI57
    per_game: Dict[str, float] = {}
    rows = []
    for game in games:
        summary = train_one_game(f"atari:{game}", f"atari57_{game}", base_args)
        raw = summary.get("eval_score_mean")
        if raw is not None:
            per_game[game] = raw
            rows.append({
                "game": game,
                "score_mean": raw,
                "human_normalized": human_normalized_score(game, raw),
                "world_record_normalized": world_record_normalized(game, raw),
                "record_provenance": RECORD_PROVENANCE.get(game, "none"),
                **{k: v for k, v in summary.items() if k.startswith("eval_")},
            })
    write_results_csv(os.path.join(results_dir, "per_game.csv"), rows)
    agg = aggregate(per_game, include_recon_records=include_recon_records)
    with open(os.path.join(results_dir, "aggregate.json"), "w") as f:
        json.dump(agg, f, indent=2)
    return agg
