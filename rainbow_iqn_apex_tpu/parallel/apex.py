"""TPU-native Ape-X: the device slice is simultaneously the learner and the
actor fleet.

Parity map (SURVEY.md §2 rows 6-8, §3.1-3.2, §5 "Distributed communication
backend"; north star BASELINE.json:5):

  reference (PyTorch + Redis)            this module (JAX/XLA)
  -----------------------------------    -----------------------------------
  1 learner process on GPU               learn step jit-sharded over the
                                         learner mesh axis "dp" (batch split,
                                         params replicated, gradient
                                         all-reduce inserted by XLA over ICI)
  N actor processes on CPUs              batched vector-env lanes, inference
                                         jit-sharded lane-wise over the actor
                                         mesh axis "actor"
  Redis experience append (TCP)          host-DRAM sharded replay append
  Redis batch fetch + priority write     local shard sample + write-back
  Redis weight mailbox (~10MB fp32)      device_put of bf16 params from the
                                         learner mesh to the actor mesh
                                         (one ICI broadcast per publish)
  actor-side initial priorities          n-step TD estimate from the actor's
  (Ape-X paper §3)                       own Q outputs, no extra forward pass

Single-host multi-device SPMD; multi-host (jax.distributed over DCN) reuses
the same code with per-host replay shards — the shard topology is already
host-aligned.
"""

from __future__ import annotations

import collections
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from rainbow_iqn_apex_tpu.agents.agent import FrameStacker, to_device_batch
from rainbow_iqn_apex_tpu.utils.prefetch import BatchPrefetcher, make_replay_prefetcher
from rainbow_iqn_apex_tpu.config import Config
from rainbow_iqn_apex_tpu.envs import make_vector_env
from rainbow_iqn_apex_tpu.ops.learn import (
    Batch,
    TrainState,
    build_act_step,
    build_learn_step,
    init_train_state,
)
from rainbow_iqn_apex_tpu.parallel.mesh import (
    actor_mesh,
    batch_sharding,
    learner_mesh,
    replicated,
    split_devices,
)
from rainbow_iqn_apex_tpu.parallel.sharded_replay import ShardedReplay
from rainbow_iqn_apex_tpu.utils.checkpoint import Checkpointer
from rainbow_iqn_apex_tpu.utils.logging import MetricsLogger


class ActorPriorityEstimator:
    """Ape-X actor-side initial priorities from the actor's own Q outputs.

    Buffers n+1 ticks of (Q(s, a_sel), reward, terminal) per lane; when the
    replay completes the transition started n ticks ago, emits
        |R_n + gamma^n * maxQ(s_now) * alive - Q(s_then, a_then)|
    with the same truncate-at-terminal rules the replay applies.
    """

    def __init__(self, lanes: int, n_step: int, gamma: float):
        self.n = n_step
        self.gamma = gamma
        self.q_sel = collections.deque(maxlen=n_step + 1)  # each [L]
        self.rew = collections.deque(maxlen=n_step + 1)
        self.term = collections.deque(maxlen=n_step + 1)

    def push(
        self,
        q_values: np.ndarray,  # [L, A] actor Q estimates at s_t
        actions: np.ndarray,  # [L]
        rewards: np.ndarray,  # [L] r_t
        terminals: np.ndarray,  # [L] d_t
    ) -> Optional[np.ndarray]:
        L = actions.shape[0]
        self.q_sel.append(q_values[np.arange(L), actions])
        self.rew.append(rewards.astype(np.float32))
        self.term.append(terminals.astype(bool))
        if len(self.rew) <= self.n:
            return None
        # window ticks: t-n .. t-1 rewards, bootstrap at t
        r = np.stack(list(self.rew))[:-1]  # [n, L] == r_{t-n..t-1}
        d = np.stack(list(self.term))[:-1]  # [n, L]
        alive = np.cumprod(1.0 - d[:-1].astype(np.float32), axis=0)
        alive = np.concatenate([np.ones((1, L), np.float32), alive], axis=0)
        gammas = self.gamma ** np.arange(self.n, dtype=np.float32)
        rn = (r * alive * gammas[:, None]).sum(axis=0)
        no_done = 1.0 - d.any(axis=0).astype(np.float32)
        boot = (self.gamma**self.n) * q_values.max(axis=1) * no_done
        return np.abs(rn + boot - self.q_sel[0]).astype(np.float64)


class ApexDriver:
    """Owns meshes, sharded compute fns, and the stale actor-param copy."""

    def __init__(
        self,
        cfg: Config,
        num_actions: int,
        devices: Optional[Sequence[jax.Device]] = None,
        state_shape: Optional[Tuple[int, ...]] = None,
    ):
        self.cfg = cfg
        self.num_actions = num_actions
        ldevs, adevs = split_devices(devices, cfg.learner_devices)
        self.lmesh = learner_mesh(ldevs)
        self.amesh = actor_mesh(adevs)
        self.n_actor_devices = len(adevs)

        rep_l, rep_a = replicated(self.lmesh), replicated(self.amesh)
        self.key = jax.random.PRNGKey(cfg.seed)
        self.key, k_init = jax.random.split(self.key)
        state = init_train_state(cfg, num_actions, k_init, state_shape=state_shape)
        self.state: TrainState = jax.device_put(state, rep_l)

        # learner step: batch split over dp, state replicated; XLA inserts the
        # gradient all-reduce (psum over "dp") from the sharding alone.
        self._learn = jax.jit(
            build_learn_step(cfg, num_actions),
            in_shardings=(rep_l, batch_sharding(self.lmesh, "dp"), rep_l),
            donate_argnums=0,
        )
        # actor step: lanes split over the actor mesh, params replicated.
        lane_sh = batch_sharding(self.amesh, "actor")
        self._act = jax.jit(
            build_act_step(cfg, num_actions, use_noise=True),
            in_shardings=(rep_a, lane_sh, rep_a),
            out_shardings=(lane_sh, lane_sh),
        )
        if cfg.bf16_weight_sync:
            self._cast = jax.jit(
                lambda p: jax.tree.map(lambda x: x.astype(jnp.bfloat16), p)
            )
            self._uncast = jax.jit(
                lambda p: jax.tree.map(lambda x: x.astype(jnp.float32), p),
                out_shardings=rep_a,
            )
        self.actor_params = None
        self.publish_weights()  # initial broadcast

    # ------------------------------------------------------------- weight sync
    def publish_weights(self) -> None:
        """Learner -> actor-mesh broadcast (the Redis SET + actor GET pair)."""
        p = self.state.params
        if self.cfg.bf16_weight_sync:
            p = self._uncast(jax.device_put(self._cast(p), replicated(self.amesh)))
        else:
            p = jax.device_put(p, replicated(self.amesh))
        self.actor_params = p

    # ----------------------------------------------------------------- compute
    def _next_key(self):
        self.key, k = jax.random.split(self.key)
        return k

    def act_async(self, stacked_obs: np.ndarray):
        """Dispatch lane-sharded inference; returns DEVICE arrays immediately
        (JAX async dispatch) so the host can overlap env work."""
        return self._act(self.actor_params, jnp.asarray(stacked_obs), self._next_key())

    def act(self, stacked_obs: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        a, q = self.act_async(stacked_obs)
        return np.asarray(a), np.asarray(q)

    def learn(self, sample) -> Dict[str, Any]:
        return self.learn_batch(to_device_batch(sample))

    def learn_batch(self, batch: Batch) -> Dict[str, Any]:
        self.state, info = self._learn(self.state, batch, self._next_key())
        return info

    @property
    def step(self) -> int:
        return int(self.state.step)


def _eval_learner(cfg: Config, env, driver: "ApexDriver") -> Dict[str, Any]:
    """Evaluate the LEARNER's current params (reference evaluates the learner
    checkpoint, SURVEY §3.5) on a single-device eval agent."""
    from rainbow_iqn_apex_tpu.agents.agent import Agent
    from rainbow_iqn_apex_tpu.eval import evaluate

    eval_agent = Agent(
        cfg,
        env.num_actions,
        jax.random.PRNGKey(cfg.seed + 1),
        train=False,
        state_shape=(*env.frame_shape, cfg.history_length),
    )
    eval_agent.state = jax.device_put(driver.state, jax.devices()[0])
    return evaluate(cfg, eval_agent, seed=cfg.seed + 977)


def train_apex(cfg: Config, max_frames: Optional[int] = None) -> Dict[str, Any]:
    """The full Ape-X loop on one host's slice (SURVEY §3.1 + §3.2 fused)."""
    total_frames = max_frames or cfg.t_max
    lanes = cfg.num_actors * cfg.num_envs_per_actor
    env = make_vector_env(cfg.env_id, lanes, seed=cfg.seed)
    driver = ApexDriver(
        cfg, env.num_actions, state_shape=(*env.frame_shape, cfg.history_length)
    )
    if lanes % driver.n_actor_devices:
        raise ValueError(
            f"total lanes {lanes} must divide across {driver.n_actor_devices} "
            "actor devices"
        )

    memory = ShardedReplay.build(
        cfg.replay_shards,
        cfg.memory_capacity,
        lanes,
        frame_shape=env.frame_shape,
        history=cfg.history_length,
        n_step=cfg.multi_step,
        gamma=cfg.gamma,
        priority_exponent=cfg.priority_exponent,
        priority_eps=cfg.priority_eps,
        seed=cfg.seed,
        use_native=cfg.use_native_sumtree,
    )
    import os

    from rainbow_iqn_apex_tpu.train import priority_beta

    run_dir = os.path.join(cfg.results_dir, cfg.run_id)
    metrics = MetricsLogger(os.path.join(run_dir, "metrics.jsonl"), cfg.run_id)
    ckpt = Checkpointer(os.path.join(cfg.checkpoint_dir, cfg.run_id))

    estimator = (
        ActorPriorityEstimator(lanes, cfg.multi_step, cfg.gamma)
        if cfg.initial_priority_from_actor
        else None
    )
    stacker = FrameStacker(lanes, env.frame_shape, cfg.history_length)
    obs = env.reset()
    returns: collections.deque = collections.deque(maxlen=100)
    frames = 0
    last_pub = 0
    prefetcher: Optional[BatchPrefetcher] = None

    pending = None  # pipelined: device (actions, q) dispatched last tick
    held = None  # pipelined: completed transition awaiting its Q for append
    try:
        while frames < total_frames:
            stacked = stacker.push(obs)
            if cfg.pipelined_actor:
                # Overlap: dispatch inference for THIS obs; execute the action
                # computed from the PREVIOUS obs (one-tick behaviour lag; the
                # first tick primes the pipe synchronously).
                nxt = driver.act_async(stacked)
                if pending is None:
                    pending = nxt
                actions = np.asarray(pending[0])
            else:
                actions, q = driver.act(stacked)
            new_obs, rewards, terminals, truncs, ep_returns = env.step(actions)
            cuts = terminals | truncs  # truncation cuts windows like a terminal
            if cfg.pipelined_actor:
                # The transition (s_t, a_t, r_t) needs Q(s_t) — that's `nxt`,
                # still computing while the envs stepped. Hold the transition
                # one tick and append it when its Q has certainly landed, so
                # actor-side priorities use the RIGHT observation's values
                # (only the behaviour policy is stale, not the estimates).
                if held is not None:
                    h_obs, h_act, h_rew, h_term, h_trunc, h_q = held
                    pri = (
                        estimator.push(np.asarray(h_q), h_act, h_rew, h_term | h_trunc)
                        if estimator
                        else None
                    )
                    memory.append_batch(
                        h_obs, h_act, h_rew, h_term, pri, truncations=h_trunc
                    )
                held = (obs, actions, rewards, terminals, truncs, nxt[1])
                pending = nxt
            else:
                pri = estimator.push(q, actions, rewards, cuts) if estimator else None
                memory.append_batch(obs, actions, rewards, terminals, pri, truncations=truncs)
            stacker.reset_lanes(cuts)
            obs = new_obs
            frames += lanes
            for r in ep_returns[~np.isnan(ep_returns)]:
                returns.append(float(r))

            if len(memory) >= cfg.learn_start and memory.sampleable:
                if cfg.prefetch_depth > 0 and prefetcher is None:
                    prefetcher = make_replay_prefetcher(
                        memory, cfg, lambda: priority_beta(cfg, frames)
                    )
                steps_due = frames // cfg.replay_ratio - driver.step
                for _ in range(max(steps_due, 0)):
                    if prefetcher is not None:
                        idx, batch = prefetcher.get()
                        info = driver.learn_batch(batch)
                    else:
                        sample = memory.sample(cfg.batch_size, priority_beta(cfg, frames))
                        idx = sample.idx
                        info = driver.learn(sample)
                    memory.update_priorities(idx, np.asarray(info["priorities"]))
                    step = driver.step
                    if step - last_pub >= cfg.weight_publish_interval:
                        driver.publish_weights()
                        last_pub = step
                    if step % cfg.metrics_interval == 0:
                        metrics.log(
                            "train",
                            step=step,
                            frames=frames,
                            fps=metrics.fps(frames),
                            loss=float(info["loss"]),
                            q_mean=float(info["q_mean"]),
                            mean_return=float(np.mean(returns)) if returns else float("nan"),
                            staleness=step - last_pub,
                        )
                    if cfg.eval_interval and step % cfg.eval_interval == 0:
                        metrics.log(
                            "eval", step=step, **_eval_learner(cfg, env, driver)
                        )
                    if cfg.checkpoint_interval and step % cfg.checkpoint_interval == 0:
                        ckpt.save(step, driver.state, {"frames": frames})

    finally:
        if prefetcher is not None:
            prefetcher.close()
    final_eval = _eval_learner(cfg, env, driver)
    metrics.log("eval", step=driver.step, **final_eval)
    ckpt.save(driver.step, driver.state, {"frames": frames})
    ckpt.wait()
    metrics.close()
    return {
        "frames": frames,
        "learn_steps": driver.step,
        "lanes": lanes,
        "train_return_mean": float(np.mean(returns)) if returns else float("nan"),
        **{f"eval_{k}": v for k, v in final_eval.items()},
    }
