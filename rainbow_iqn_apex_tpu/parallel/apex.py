"""TPU-native Ape-X: the device slice is simultaneously the learner and the
actor fleet.

Parity map (SURVEY.md §2 rows 6-8, §3.1-3.2, §5 "Distributed communication
backend"; north star BASELINE.json:5):

  reference (PyTorch + Redis)            this module (JAX/XLA)
  -----------------------------------    -----------------------------------
  1 learner process on GPU               learn step jit-sharded over the
                                         learner mesh axis "dp" (batch split,
                                         params replicated, gradient
                                         all-reduce inserted by XLA over ICI)
  N actor processes on CPUs              batched vector-env lanes, inference
                                         jit-sharded lane-wise over the actor
                                         mesh axis "actor"
  Redis experience append (TCP)          host-DRAM sharded replay append
  Redis batch fetch + priority write     local shard sample + write-back
  Redis weight mailbox (~10MB fp32)      device_put of bf16 params from the
                                         learner mesh to the actor mesh
                                         (one ICI broadcast per publish)
  actor-side initial priorities          n-step TD estimate from the actor's
  (Ape-X paper §3)                       own Q outputs, no extra forward pass

Single-host multi-device SPMD; multi-host (jax.distributed over DCN) reuses
the same code with per-host replay shards — the shard topology is already
host-aligned.
"""

from __future__ import annotations

import collections
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from rainbow_iqn_apex_tpu.agents.agent import (
    FrameStacker,
    put_frames,
    to_device_batch,
)
from rainbow_iqn_apex_tpu.utils.prefetch import BatchPrefetcher, make_replay_prefetcher
from rainbow_iqn_apex_tpu.utils import hostsync
from rainbow_iqn_apex_tpu.utils.writeback import (
    RingCommitter,
    WritebackRing,
    cadence_hit,
    check_reuse_cadences,
    pipeline_gauges,
    reuse_health,
    reuse_learn_row,
)
from rainbow_iqn_apex_tpu.config import Config
from rainbow_iqn_apex_tpu.envs import make_vector_env
from rainbow_iqn_apex_tpu.obs import RunObs
from rainbow_iqn_apex_tpu.ops.learn import (
    Batch,
    TrainState,
    build_act_step,
    build_learn_step,
    init_train_state,
)
from rainbow_iqn_apex_tpu.parallel.mesh import (
    actor_mesh,
    batch_sharding,
    learner_mesh,
    replicated,
    split_devices,
)
from rainbow_iqn_apex_tpu.parallel.quant_publish import QuantPublishMixin
from rainbow_iqn_apex_tpu.parallel.sharded_replay import ShardedReplay
from rainbow_iqn_apex_tpu.parallel.supervisor import TrainSupervisor
from rainbow_iqn_apex_tpu.utils import faults
from rainbow_iqn_apex_tpu.utils.quantize import wrap_act_quantized
from rainbow_iqn_apex_tpu.utils.checkpoint import (
    Checkpointer,
    maybe_restore_replay,
    maybe_resume,
    rng_extra,
    rng_from_extra,
)
from rainbow_iqn_apex_tpu.utils.logging import MetricsLogger


from rainbow_iqn_apex_tpu.parallel.multihost import (  # noqa: E402
    global_is_nq,
    host_state,
    lane_put,
    local_rows as _local_rows,
    make_global_is_weights,
    plan_hosts,
    shift_stack,
)


class ActorPriorityEstimator:
    """Ape-X actor-side initial priorities from the actor's own Q outputs.

    Buffers n+1 ticks of (Q(s, a_sel), reward, terminal) per lane; when the
    replay completes the transition started n ticks ago, emits
        |R_n + gamma^n * maxQ(s_now) * alive - Q(s_then, a_then)|
    with the same truncate-at-terminal rules the replay applies.
    """

    def __init__(self, lanes: int, n_step: int, gamma: float):
        self.n = n_step
        self.gamma = gamma
        self.q_sel = collections.deque(maxlen=n_step + 1)  # each [L]
        self.rew = collections.deque(maxlen=n_step + 1)
        self.term = collections.deque(maxlen=n_step + 1)

    def push(
        self,
        q_values: np.ndarray,  # [L, A] actor Q estimates at s_t
        actions: np.ndarray,  # [L]
        rewards: np.ndarray,  # [L] r_t
        terminals: np.ndarray,  # [L] d_t
    ) -> Optional[np.ndarray]:
        L = actions.shape[0]
        self.q_sel.append(q_values[np.arange(L), actions])
        self.rew.append(rewards.astype(np.float32))
        self.term.append(terminals.astype(bool))
        if len(self.rew) <= self.n:
            return None
        # window ticks: t-n .. t-1 rewards, bootstrap at t
        r = np.stack(list(self.rew))[:-1]  # [n, L] == r_{t-n..t-1}
        d = np.stack(list(self.term))[:-1]  # [n, L]
        alive = np.cumprod(1.0 - d[:-1].astype(np.float32), axis=0)
        alive = np.concatenate([np.ones((1, L), np.float32), alive], axis=0)
        gammas = self.gamma ** np.arange(self.n, dtype=np.float32)
        rn = (r * alive * gammas[:, None]).sum(axis=0)
        no_done = 1.0 - d.any(axis=0).astype(np.float32)
        boot = (self.gamma**self.n) * q_values.max(axis=1) * no_done
        return np.abs(rn + boot - self.q_sel[0]).astype(np.float64)


class ApexDriver(QuantPublishMixin):
    """Owns meshes, sharded compute fns, and the stale actor-param copy.

    The gated quantized publish surface (publish_weights, attach_obs,
    calibration handshake, quant/publish rows) is the shared
    `QuantPublishMixin` — the two apex drivers must not drift on it."""

    def __init__(
        self,
        cfg: Config,
        num_actions: int,
        devices: Optional[Sequence[jax.Device]] = None,
        state_shape: Optional[Tuple[int, ...]] = None,
        spec=None,  # multitask.MultiGameSpec: task-conditioned multi-game mode
    ):
        self.cfg = cfg
        self.num_actions = num_actions
        self.spec = spec
        # replay reuse (ops/learn.py make_reuse_learn_step): one learn
        # dispatch = a fused K-pass executable, so state.step — and the
        # host step mirror — advance K per learn_batch call
        self.reuse_k = max(int(cfg.replay_ratio), 1)
        ldevs, adevs = split_devices(devices, cfg.learner_devices)
        self.lmesh = learner_mesh(ldevs)
        self.amesh = actor_mesh(adevs)
        self.n_actor_devices = len(adevs)

        rep_l, rep_a = replicated(self.lmesh), replicated(self.amesh)
        self._rep_l = rep_l  # league retune rebuilds the learn jit in place
        self.key = jax.random.PRNGKey(cfg.seed)
        self.key, k_init = jax.random.split(self.key)
        if spec is not None:
            # task-conditioned learner (multitask/; docs/MULTITASK.md):
            # MultiGameIQN with a game-id embedding, ONE jitted dispatch for
            # the whole suite — game ids are data, shapes are suite-common,
            # so XLA compiles once per role regardless of how many games run
            from rainbow_iqn_apex_tpu.multitask.ops import (
                build_mt_act_step,
                build_mt_learn_step,
                init_mt_train_state,
            )

            state = init_mt_train_state(cfg, spec, k_init)
            learn_fn = build_mt_learn_step(cfg, spec)
            act_fn = build_mt_act_step(cfg, spec, use_noise=True)
        else:
            state = init_train_state(
                cfg, num_actions, k_init, state_shape=state_shape)
            learn_fn = build_learn_step(cfg, num_actions)
            act_fn = build_act_step(cfg, num_actions, use_noise=True)
        self._host_step: Optional[int] = None  # host mirror of state.step
        self.state: TrainState = jax.device_put(state, rep_l)

        # learner step: batch split over dp, state replicated; XLA inserts the
        # gradient all-reduce (psum over "dp") from the sharding alone.
        self._batch_sh = batch_sharding(self.lmesh, "dp")
        self._learn = jax.jit(
            learn_fn,
            in_shardings=(rep_l, self._batch_sh, rep_l),
            donate_argnums=0,
        )
        # actor step: lanes split over the actor mesh, params replicated.
        # Multi-game acting threads a lane-sharded [L] game-id vector
        # (set_lane_games) through the same executable.
        lane_sh = batch_sharding(self.amesh, "actor")
        self._lane_sh = lane_sh
        self._lane_games = None  # device [L] i32, mt mode only

        # device-resident frame stacking: the stack never leaves the actor
        # mesh; the host ships ONE [L, H, W] frame per tick and lanes cut
        # last tick are zeroed in-graph before the shift — bit-identical to
        # the host FrameStacker (tests/test_parallel.py), 4x less transfer,
        # and none of the strided host shifting that was the measured host
        # bottleneck (~14k frames/s on the build sandbox vs ~130k replay
        # append).  One wiring for both act flavours: multi-game threads
        # one extra lane-sharded [L] game-id operand through the same
        # executables (fp32 and quantized twins alike).
        def jit_act_pair(fn):
            game_sh = (lane_sh,) if spec is not None else ()

            def stack_act(params, stack, frame, keep, *rest):
                # rest = (game, key) in multi-game mode, (key,) otherwise
                stack = shift_stack(stack, frame, keep)
                a, q = fn(params, stack, *rest)
                return a, q, stack

            act = jax.jit(
                fn,
                in_shardings=(rep_a, lane_sh, *game_sh, rep_a),
                out_shardings=(lane_sh, lane_sh),
            )
            stack = jax.jit(
                stack_act,
                in_shardings=(
                    rep_a, lane_sh, lane_sh, lane_sh, *game_sh, rep_a),
                out_shardings=(lane_sh, lane_sh, lane_sh),
                donate_argnums=1,
            )
            return act, stack

        self._act, self._stack_act = jit_act_pair(act_fn)
        self._put_lanes = lane_put(lane_sh)
        self.actor_stack = None  # created lazily at the first act_frames
        # quantized actor lanes (utils/quantize.py + the shared
        # QuantPublishMixin; cfg.serve_quantize): publishes ship int8 (4x
        # less ICI/DCN traffic than fp32) and the actor act step
        # dequantizes inside its own executable — guarded by the
        # greedy-action agreement gate on a replay-drawn calibration batch.
        self._rep_a = rep_a
        if self._init_quant_publish(
                cfg, multihost=jax.process_count() > 1) != "off":
            act_q_fn = wrap_act_quantized(act_fn)
            self._act_q, self._stack_act_q = jit_act_pair(act_q_fn)
            # the gate runs on the LEARNER mesh copy (plain jit)
            self._gate_act32 = jax.jit(act_fn)
            self._gate_actq = jax.jit(act_q_fn)
        if cfg.bf16_weight_sync:
            self._cast = jax.jit(
                lambda p: jax.tree.map(lambda x: x.astype(jnp.bfloat16), p)
            )
            self._uncast = jax.jit(
                lambda p: jax.tree.map(lambda x: x.astype(jnp.float32), p),
                out_shardings=rep_a,
            )
        # multi-host: global IS-weight renormalization (shared helper so the
        # two apex drivers can't drift)
        self._global_is_weights = make_global_is_weights(self._batch_sh)
        self.actor_params = None
        # weight-staleness fencing (parallel/elastic.py): every publish
        # stamps a monotonically increasing version so actors — in-process
        # or external (WeightMailbox readers) — can measure their lag in
        # publishes and fence past cfg.max_weight_lag
        self.weights_version = 0
        self.actor_weights_version = 0
        self.publish_weights()  # initial broadcast

    # ------------------------------------------------------------- weight sync
    # publish_weights / attach_obs / wants_calibration and the gated
    # quantized broadcast live in QuantPublishMixin (shared with the r2d2
    # driver); only the act-signature-shaped hooks are defined here.
    def set_lane_games(self, games: np.ndarray) -> None:
        """Multi-game mode: pin the [L] per-lane game ids (lane-sharded
        device constant every act dispatch conditions on).  Must match the
        lane order of `multitask.build_game_lanes`."""
        self._lane_games = self._put_lanes(np.asarray(games, np.int32))

    @property
    def _game_args(self) -> tuple:
        """The extra act-step operand(s): one lane-sharded game-id vector
        in multi-game mode, nothing otherwise — splatted at every act call
        site so the two modes share one call shape."""
        return () if self._lane_games is None else (self._lane_games,)

    def set_calibration(self, obs_batch: np.ndarray,
                        game: Optional[np.ndarray] = None) -> None:
        """Calibration observations for the agreement gate, drawn from
        replay statistics (a sampled batch's stacked obs, plus its game ids
        in multi-game mode).  Clipped to ``cfg.quant_calib_batch`` so the
        gate executables compile once."""
        n = min(len(obs_batch), max(int(self.cfg.quant_calib_batch), 1))
        self._calib_obs = jnp.asarray(np.asarray(obs_batch[:n], np.uint8))
        if self.spec is not None:
            if game is None:
                game = np.zeros(n, np.int32)
            self._calib_game = jnp.asarray(
                np.asarray(game[:n], np.int32))

    def _gate_actions(self, params, qparams):
        calib = (self._calib_obs, *(
            (self._calib_game,) if self.spec is not None else ()))
        a32, _ = self._gate_act32(params, *calib, self._gate_key)
        aq, _ = self._gate_actq(qparams, *calib, self._gate_key)
        return a32, aq

    # ---------------------------------------------------------------- resume
    def load_state(self, state, extra: Optional[Dict[str, Any]] = None) -> None:
        """Place a restored TrainState onto the learner mesh, pick up the
        saved RNG stream when the checkpoint carries one, and re-publish
        actor weights.  The weight-version counter resumes from the
        checkpoint too — a restarted learner must publish versions ABOVE the
        ones out-of-process actors already hold, or the staleness fence's
        lag arithmetic fails open exactly in the restart window."""
        self.state = jax.device_put(state, replicated(self.lmesh))
        self.key = jnp.asarray(rng_from_extra(extra or {}, self.key))
        saved = int((extra or {}).get("weights_version", 0))
        self.weights_version = max(self.weights_version, saved)
        self.publish_weights()

    def restore(self, ckpt) -> Dict[str, Any]:
        """Load the latest checkpoint into the learner mesh and re-publish
        actor weights; returns the checkpoint's extra metadata."""
        state, extra = ckpt.restore(self.state)
        self.load_state(state, extra)
        return extra

    # ------------------------------------------------------- league adoption
    def adopt_params(self, host_params) -> None:
        """League exploit adoption (league/member.py, docs/LEAGUE.md):
        replace online AND target params with the copied member's weights
        and re-publish so the actor lanes act on them immediately.  Called
        only at a drained boundary (no unverified step in flight).  Adam
        moments re-init fresh — the loser's statistics are meaningless at
        the winner's point in weight space, and a deterministic re-init is
        reproducible where stale moments are not.  Step counter, PRNG
        stream, and weight-version counter all continue (the version keeps
        rising, so out-of-process staleness fences never see a rollback)."""
        from rainbow_iqn_apex_tpu.league.member import graft_tree
        from rainbow_iqn_apex_tpu.ops.learn import make_optimizer

        params = graft_tree(host_state(self.state).params, host_params)
        params = jax.device_put(params, replicated(self.lmesh))
        self.state = self._state.replace(
            params=params,
            target_params=jax.tree.map(jnp.copy, params),
            opt_state=jax.jit(
                make_optimizer(self.cfg).init,
                out_shardings=self._rep_l)(params),
        )
        self.publish_weights()

    def retune(self, learning_rate: Optional[float] = None) -> None:
        """Mid-run live-gene adoption: rebuild the jitted learn step under
        the new learning rate (one recompile per exploit event — rare by
        construction).  Replay-side genes (n_step, priority_exponent) are
        retuned on the replay by the loop; restart genes (replay_ratio,
        schedule) wait for the next respawn's config overlay."""
        if learning_rate is None:
            return
        self.cfg = self.cfg.replace(learning_rate=float(learning_rate))
        if self.spec is not None:
            from rainbow_iqn_apex_tpu.multitask.ops import build_mt_learn_step

            learn_fn = build_mt_learn_step(self.cfg, self.spec)
        else:
            learn_fn = build_learn_step(self.cfg, self.num_actions)
        self._learn = jax.jit(
            learn_fn,
            in_shardings=(self._rep_l, self._batch_sh, self._rep_l),
            donate_argnums=0,
        )

    # ---------------------------------------------------------------- rollback
    def load_snapshot(self, state, key) -> None:
        """NaN-guard rollback (parallel/supervisor.py): last-good host state
        back onto the learner mesh.  Actor params are NOT re-published — the
        poisoned state was never published (the guard runs before the
        publish), so actors still hold good, merely stale, weights."""
        self.state = jax.device_put(state, replicated(self.lmesh))
        self.key = jnp.asarray(key)

    # ----------------------------------------------------------------- compute
    def _next_key(self):
        self.key, k = jax.random.split(self.key)
        return k

    def act_async(self, stacked_obs: np.ndarray):
        """Dispatch lane-sharded inference; returns DEVICE arrays immediately
        (JAX async dispatch) so the host can overlap env work."""
        act = self._act_q if self._actor_quant else self._act
        return act(self.actor_params, put_frames(stacked_obs),
                   *self._game_args, self._next_key())

    def act(self, stacked_obs: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        a, q = self.act_async(stacked_obs)
        # the actor->env hand-off is an OBLIGATORY host materialization (the
        # vector env lives on host) — a sanctioned sync on the actor half,
        # not a learner-hot-path regression (docs/PERFORMANCE.md inventory)
        with hostsync.sanctioned():
            return np.asarray(a), np.asarray(q)

    def act_frames(
        self, frames: np.ndarray, prev_cuts: np.ndarray
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Device-stacked acting: push this host's newest [L_local, H, W]
        frames into the device-resident stack (zeroing lanes whose episode
        was cut LAST tick, matching FrameStacker.reset_lanes ordering) and
        act on the result."""
        if self.actor_stack is None:
            h, w = frames.shape[1], frames.shape[2]
            self.actor_stack = self._put_lanes(
                np.zeros((frames.shape[0], h, w, self.cfg.history_length), np.uint8)
            )
        keep = self._put_lanes((~np.asarray(prev_cuts, bool)).astype(np.uint8))
        stack_act = self._stack_act_q if self._actor_quant else self._stack_act
        a, q, self.actor_stack = stack_act(
            self.actor_params,
            self.actor_stack,
            self._put_lanes(np.asarray(frames, np.uint8)),
            keep,
            *self._game_args,
            self._next_key(),
        )
        with hostsync.sanctioned():  # obligatory actor->env hand-off
            if jax.process_count() > 1:
                return _local_rows(a), _local_rows(q)
            return np.asarray(a), np.asarray(q)

    def learn(self, sample) -> Dict[str, Any]:
        return self.learn_batch(to_device_batch(sample))

    def learn_batch(self, batch: Batch) -> Dict[str, Any]:
        """Dispatch one learn step; ``info`` values stay DEVICE arrays (JAX
        async dispatch) — the write-back ring decides when to sync."""
        self._state, info = self._learn(self._state, batch, self._next_key())
        if self._host_step is not None:
            self._host_step += self.reuse_k
        return info

    # ------------------------------------------------------------- multi-host
    # Every pod host runs this same program (SPMD): each host contributes its
    # LOCAL sub-batch / env lanes, jax assembles the global arrays over the
    # process-spanning mesh, and the only cross-host traffic is the gradient
    # all-reduce XLA inserts (the Redis TCP fabric replaced by ICI/DCN
    # collectives — SURVEY §2 rows 6-7, §5 backend mapping).
    def learn_local(
        self,
        sample,
        global_size: Optional[int] = None,
        beta: Optional[float] = None,
    ) -> Dict[str, Any]:
        """Learn step fed from this host's local sub-batch (B/hosts rows).
        Returns info with ``priorities`` as the GLOBAL dp-sharded device
        array; pass ``multihost.local_rows`` as the write-back ring's
        ``priorities_to_host`` to get this host's rows (input order) at
        retirement.

        IS weights: each host's replay normalizes weights over its OWN
        sub-batch, which is inconsistent across hosts (each host's max row
        gets 1.0 regardless of its true global weight).  When
        ``global_size``/``beta`` are given, weights are re-derived in-graph
        over the assembled GLOBAL batch from the per-row sample
        probabilities: q(i) = prob_local(i) / n_hosts (the fixed per-host
        quota makes the scheme a uniform mixture over hosts), w = (N q)^-b
        max-normalized across all hosts — the cross-host max is one tiny
        XLA collective.
        """
        put = lambda x, dt: jax.make_array_from_process_local_data(  # noqa: E731
            self._batch_sh, np.ascontiguousarray(x, dt)
        )
        if global_size is not None and sample.prob is not None:
            nq = put(global_is_nq(sample.prob, global_size), np.float32)
            weight = self._global_is_weights(nq, jnp.float32(beta))
        else:
            weight = put(sample.weight, np.float32)
        batch = Batch(
            obs=put(sample.obs, np.uint8),
            action=put(sample.action, np.int32),
            reward=put(sample.reward, np.float32),
            next_obs=put(sample.next_obs, np.uint8),
            discount=put(sample.discount, np.float32),
            weight=weight,
        )
        # priorities stay the GLOBAL device array: the write-back ring
        # extracts this host's local rows at RETIREMENT (K steps later) via
        # its priorities_to_host hook, so dispatching a multi-host learn
        # step blocks on nothing either
        return self.learn_batch(batch)

    def act_local(self, stacked_obs: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """Lane-sharded inference fed from this host's local lanes."""
        obs = self._put_lanes(stacked_obs)
        act = self._act_q if self._actor_quant else self._act
        a, q = act(self.actor_params, obs, self._next_key())
        with hostsync.sanctioned():  # obligatory actor->env hand-off
            return _local_rows(a), _local_rows(q)

    # `state` invalidates the host step mirror on direct assignment
    # (load_state / load_snapshot / tests); learn_batch bypasses the setter
    # and increments the mirror, so the hot loop's per-step `driver.step`
    # reads never block on the device queue.
    @property
    def state(self) -> TrainState:
        return self._state

    @state.setter
    def state(self, value: TrainState) -> None:
        self._state = value
        self._host_step = None

    @property
    def step(self) -> int:
        if self._host_step is None:
            with hostsync.sanctioned():
                self._host_step = int(np.asarray(self._state.step))
        return self._host_step


def _eval_learner(cfg: Config, env, driver: "ApexDriver") -> Dict[str, Any]:
    """Evaluate the LEARNER's current params (reference evaluates the learner
    checkpoint, SURVEY §3.5) on a single-device eval agent."""
    from rainbow_iqn_apex_tpu.eval import evaluate_state

    return evaluate_state(cfg, env, host_state(driver.state), seed=cfg.seed + 977)


def _eval_multigame(cfg: Config, spec, driver: "ApexDriver",
                    metrics, step: int, games_obs) -> Dict[str, Any]:
    """Multi-game eval emission (docs/MULTITASK.md): one `eval` row PER
    GAME (keyed by ``game``) plus one `eval_mt` aggregate row carrying the
    suite human-normalized median/mean — the Atari-57 reporting convention.
    Returns the flat aggregate dict for the run summary."""
    from rainbow_iqn_apex_tpu.multitask.eval import evaluate_multigame

    res = evaluate_multigame(
        cfg, spec, host_state(driver.state).params, seed=cfg.seed + 977)
    games_obs.note_eval(res)
    if metrics is not None:
        for name, row in res["games"].items():
            metrics.log("eval", step=step, game=name, **row)
        metrics.log(
            "eval_mt", step=step, score_mean=res["score_mean"],
            hn_median=res["hn_median"], hn_mean=res["hn_mean"],
            hn_games=res["hn_games"], games=len(res["games"]),
        )
    return {
        "score_mean": res["score_mean"],
        "hn_median": res["hn_median"],
        "hn_mean": res["hn_mean"],
        "hn_games": res["hn_games"],
    }


def train_apex(cfg: Config, max_frames: Optional[int] = None) -> Dict[str, Any]:
    """The full Ape-X loop on one host's slice (SURVEY §3.1 + §3.2 fused).

    Multi-host (cfg.process_count > 1): every pod host runs this SAME loop in
    lockstep over a process-spanning mesh — each host steps its slice of the
    env lanes, appends to its LOCAL replay shard, and contributes its local
    sub-batch to the dp-sharded learn step; the gradient all-reduce XLA
    inserts over ICI/DCN is the only cross-host traffic (SURVEY §2 rows 6-7:
    the reference's remote Redis actors, re-shaped).  Requires
    learner_devices == 0 (both roles on every chip) so the weight publish
    stays host-local.
    """
    # league membership (league/; docs/LEAGUE.md): validate the league_*
    # spec and overlay this member's genome BEFORE any component reads a
    # hyperparameter (replay_ratio below derives reuse_k from the overlaid
    # cfg).  Default-off takes none of this — `member` stays None and the
    # loop is bitwise the pre-league path (tier-1 asserted).
    from rainbow_iqn_apex_tpu.league.member import LeagueMember
    from rainbow_iqn_apex_tpu.league.population import check_league_config

    check_league_config(cfg)
    member = LeagueMember.from_config(cfg)
    if member is not None:
        # genome n_step must respect the ring geometry (per-shard seg =
        # capacity // lanes regardless of the shard split; members are
        # single-host so the whole capacity/lane space is this process's)
        # or the replay build below crash-loops every respawn
        member.clamp_n_step(
            cfg.memory_capacity
            // (cfg.num_actors * cfg.num_envs_per_actor)
            - cfg.history_length - 1)
        cfg = member.overlay(cfg)
    total_frames = max_frames or cfg.t_max
    lanes_total = cfg.num_actors * cfg.num_envs_per_actor
    plan = plan_hosts(cfg, lanes_total)
    multihost, nproc = plan.multihost, plan.nproc
    lanes, lane_lo = plan.lanes, plan.lane_lo
    is_main, local_batch = plan.is_main, plan.local_batch

    # multi-game mode (multitask/; docs/MULTITASK.md): N games in one pod —
    # per-game lane blocks, a task-conditioned learner, game-pinned replay
    # shards behind the interleave schedule, per-game eval/obs rows.  Unset
    # games (the default) touches NONE of this: the single-game path below
    # is bitwise the pre-multitask loop (tier-1 asserted).
    from rainbow_iqn_apex_tpu.multitask.spec import MultiGameSpec

    spec = MultiGameSpec.from_config(cfg)
    if spec is not None and multihost:
        raise ValueError(
            "multi-game apex (cfg.games) is single-host for now — per-host "
            "game partitioning of an SPMD pod is the ROADMAP follow-up")
    if member is not None and multihost:
        raise ValueError(
            "league members (cfg.league_member_id) are single-host for now "
            "— a member IS one pod's trainer; partitioning one member over "
            "an SPMD pod while the controller swaps its weights mid-run is "
            "the ROADMAP follow-up (docs/LEAGUE.md)")
    games_obs = None
    if spec is not None:
        from rainbow_iqn_apex_tpu.multitask.lanes import (
            build_game_lanes,
            lane_games,
        )
        from rainbow_iqn_apex_tpu.multitask.obs import GamesObs

        if lanes % spec.num_games:
            raise ValueError(
                f"total lanes {lanes} must divide across "
                f"{spec.num_games} games")
        env = build_game_lanes(
            spec, lanes // spec.num_games, seed=cfg.seed + lane_lo)
        games_obs = GamesObs(spec)
    else:
        # per-lane seeds are carved from the GLOBAL lane space so hosts
        # never duplicate env streams
        env = make_vector_env(cfg.env_id, lanes, seed=cfg.seed + lane_lo)
    driver = ApexDriver(
        cfg, env.num_actions,
        state_shape=(*env.frame_shape, cfg.history_length), spec=spec,
    )
    if lanes_total % driver.n_actor_devices:
        raise ValueError(
            f"total lanes {lanes_total} must divide across "
            f"{driver.n_actor_devices} actor devices"
        )
    if spec is not None:
        driver.set_lane_games(lane_games(spec, lanes // spec.num_games))

    if spec is not None:
        from rainbow_iqn_apex_tpu.multitask.replay import MultiGameReplay

        # cfg.replay_shards is PER GAME here: each game owns its own shard
        # block (its per-game priority trees), so one game's drop/readmit
        # never touches a sibling's sampling distribution
        shards = max(cfg.replay_shards, 1) * spec.num_games
        memory = MultiGameReplay.build_games(
            spec,
            max(cfg.replay_shards, 1),
            cfg.memory_capacity,
            lanes,
            schedule=cfg.multitask_schedule,
            history=cfg.history_length,
            n_step=cfg.multi_step,
            gamma=cfg.gamma,
            priority_exponent=cfg.priority_exponent,
            priority_eps=cfg.priority_eps,
            seed=cfg.seed + lane_lo,
            use_native=cfg.use_native_sumtree,
        )
    else:
        shards = cfg.replay_shards // nproc if multihost else cfg.replay_shards
        memory = ShardedReplay.build(
            max(shards, 1),
            cfg.memory_capacity // nproc,
            lanes,
            frame_shape=env.frame_shape,
            history=cfg.history_length,
            n_step=cfg.multi_step,
            gamma=cfg.gamma,
            priority_exponent=cfg.priority_exponent,
            priority_eps=cfg.priority_eps,
            seed=cfg.seed + lane_lo,
            use_native=cfg.use_native_sumtree,
        )
    learn_start = cfg.learn_start // nproc  # local transitions before learning
    import os

    from rainbow_iqn_apex_tpu.train import priority_beta

    run_dir = os.path.join(cfg.results_dir, cfg.run_id)
    metrics = MetricsLogger(
        os.path.join(run_dir, "metrics.jsonl") if is_main else None,
        cfg.run_id,
        echo=is_main,
        host=cfg.process_id,
    )
    ckpt = Checkpointer(os.path.join(cfg.checkpoint_dir, cfg.run_id))
    faults.install_from(cfg)
    obs_run = RunObs(cfg, metrics, role="learner")
    memory.attach_registry(obs_run.registry)
    # pipeline tracing (obs/pipeline_trace.py): always-on lag attribution
    # (sample age, ring retirement, publish->adopt) + 1-in-N causal span
    # emission when cfg.trace_sample_every > 0 (off = bitwise seed path)
    from rainbow_iqn_apex_tpu.obs.pipeline_trace import PipelineTracer

    ptrace = PipelineTracer(
        metrics, obs_run.registry, cfg.trace_sample_every,
        host=cfg.process_id,
    )
    ptrace.max_weight_lag = cfg.max_weight_lag
    memory.attach_tracer(ptrace)
    driver.attach_obs(metrics, obs_run.registry, tracer=ptrace)
    if driver.quant_disabled_reason is not None:
        # mirrors the device_sampling multihost fallback: identical cfg on
        # every host, so the whole pod declines together (lockstep SPMD)
        metrics.log("notice", event="quant_fallback_multihost",
                    reason="multihost: fp32/bf16 publish path retained")
    # NOTE (multi-host): the injector/retry decisions are pure functions of
    # (spec, seed, call order), identical on every host — supervised control
    # flow can never diverge the SPMD program around a collective.
    sup = TrainSupervisor(cfg, metrics=metrics, registry=obs_run.registry)
    from rainbow_iqn_apex_tpu.parallel.elastic import (
        HeartbeatMonitor,
        HeartbeatWriter,
        StalenessFence,
        heartbeat_dir,
        next_lease_epoch,
    )

    heartbeat = monitor = None
    league_hb = None
    if member is not None:
        member.attach_obs(metrics, obs_run.registry)
        if cfg.heartbeat_interval_s > 0:
            # member lease under the LEAGUE dir (the controller's watch
            # point, distinct from this run's own heartbeat below): the
            # payload carries member id + exploit generation so the
            # controller reads PBT state straight off the lease
            league_hb = HeartbeatWriter(
                os.path.join(cfg.league_dir, "heartbeats"),
                cfg.league_member_id, cfg.heartbeat_interval_s,
                role="member", epoch=member.epoch,
                payload_fn=member.lease_payload,
            ).start()
    if cfg.heartbeat_interval_s > 0:
        heartbeat = HeartbeatWriter(
            heartbeat_dir(cfg), cfg.process_id, cfg.heartbeat_interval_s,
            role="apex", shard=cfg.process_id * max(shards, 1),
            # every (re)start claims a fresh incarnation epoch: a relaunched
            # host's death/revival fires as a NEW transition instead of
            # being deduped against the previous incarnation's report
            epoch=next_lease_epoch(heartbeat_dir(cfg), cfg.process_id),
            # league members stamp member/generation into this run-dir
            # lease too (parallel/elastic.py Lease.member/.generation)
            payload_fn=member.lease_payload if member is not None else None,
        )
        if spec is not None:
            # lease payloads carry the game set this host serves, so an
            # external controller (RoleSupervisor respawns, fence monitors)
            # stays game-aware without tailing this process's JSONL
            heartbeat.update_payload(game=",".join(spec.games))
        heartbeat.set_weight_version(driver.weights_version)
        heartbeat.start()
        if is_main:
            monitor = HeartbeatMonitor(
                heartbeat_dir(cfg), cfg.heartbeat_timeout_s, self_id=cfg.process_id
            )
    # learner failover (parallel/failover.py; docs/RESILIENCE.md "learner
    # failover"): claim this incarnation's learner-role epoch through the
    # same O_EXCL markers standbys race, stamp it into the lease payload
    # (the standby's takeover contract) and arm the zombie publish fence.
    # Default-off takes none of this; multihost declines with a reasoned
    # notice (N pod hosts racing one role claim would fence each other —
    # pod-level failover is a ROADMAP follow-up).
    lfence = None
    learner_epoch = 0
    if cfg.failover_standby:
        if multihost:
            metrics.log("notice", event="failover_fallback",
                        reason="multihost: external respawn loop retained")
        else:
            from rainbow_iqn_apex_tpu.parallel.elastic import EpochFence
            from rainbow_iqn_apex_tpu.parallel.failover import (
                LEARNER_ROLE,
                learner_epoch_at_start,
                refresh_fence,
            )

            learner_epoch = learner_epoch_at_start(cfg)
            lfence = EpochFence(learner_epoch)
            driver.attach_epoch_fence(lfence, learner_epoch)
            if heartbeat is not None:
                heartbeat.update_payload(
                    role=LEARNER_ROLE, learner_epoch=learner_epoch)
                heartbeat.beat()  # visible before the first renewal interval
            metrics.log("failover", event="claim", won=True,
                        epoch=learner_epoch, source="learner_start")

    def _zombie_detected(at_step: int) -> bool:
        """Refresh the learner-epoch fence from the claim markers and answer
        whether a SUCCESSOR epoch has appeared — this incarnation is then a
        zombie and must EXIT, not merely fence its publishes: a fenced loop
        that keeps training burns the device indefinitely and keeps writing
        force=True checkpoints into the same Orbax directory the successor
        owns (two concurrent CheckpointManagers — torn steps, pruning
        races).  Emits the terminal failover row on detection."""
        if lfence is None:
            return False
        refresh_fence(lfence, heartbeat_dir(cfg))
        if lfence.epoch <= learner_epoch:
            return False
        metrics.log("failover", event="zombie_exit", epoch=learner_epoch,
                    fence_epoch=lfence.epoch, step=at_step, frames=frames)
        return True

    zombie = False
    # staleness fence (parallel/elastic.py): the fused loop adopts the
    # published version atomically with the params, so lag is structurally 0
    # here and the fence can never fire — observe() keeps the
    # weight_version_lag gauge live with the same contract out-of-process
    # actors (scripts/chaos_soak.py, WeightMailbox readers) fence on.
    fence = StalenessFence(
        cfg.max_weight_lag, metrics=metrics, registry=obs_run.registry
    )

    # device-resident sample frontier (replay/frontier.py): mirror the shard
    # priority vectors into HBM, draw index batches + IS weights on device,
    # and let the sample-ahead pusher assemble/push — the learner thread
    # never walks a host sum-tree.  Off (or depth 0, or multi-host) keeps
    # the host sampling path bitwise intact.
    frontier = None
    if cfg.device_sampling and cfg.sample_ahead_depth > 0:
        if multihost:
            # per-host mirrors of a dp-sharded global draw are a follow-up;
            # an SPMD pod must not diverge on a per-host capability, so every
            # host falls back together (the cfg is identical on all hosts)
            metrics.log("notice", event="device_sampling_fallback",
                        reason="multihost: host sampling path retained")
        elif member is not None:
            # the HBM priority mirror stages deltas under the n-step window
            # geometry it was built with; a mid-run n-step adoption (a LIVE
            # league gene) would silently desync it — members keep the host
            # sampling path, which `set_n_step` re-fences correctly
            metrics.log(
                "notice", event="device_sampling_fallback",
                reason="league member: host sampling retained (mid-run "
                       "n-step adoption does not compose with the device "
                       "frontier mirror)")
        elif spec is not None and cfg.multitask_schedule != "mass":
            # the frontier's fused HBM draw is proportional to global
            # priority mass — exactly the "mass" schedule and nothing else;
            # per-game-quota schedules need the host interleave
            metrics.log(
                "notice", event="device_sampling_fallback",
                reason="multitask: game-interleaved host sampling retained "
                       "(multitask_schedule=mass composes with the device "
                       "frontier)")
        else:
            from rainbow_iqn_apex_tpu.replay.frontier import (
                DeviceSampleFrontier,
            )

            frontier = DeviceSampleFrontier.from_sharded(
                memory, registry=obs_run.registry, seed=cfg.seed + 31
            )

    # cross-host replay plane (replay/net/): appends, samples and priority
    # write-backs ride the framed-socket transport to disaggregated replay
    # shard servers discovered via leases (docs/RESILIENCE.md).  Default-
    # off; every composition hazard declines with a reasoned notice and
    # keeps the in-process path bitwise intact.
    rplane = None
    if cfg.replay_net_remote:
        if multihost:
            # per-host lane->shard pinning across a pod is a follow-up; an
            # SPMD pod must not diverge on a per-host capability, so every
            # host falls back together
            metrics.log("notice", event="replay_net_fallback",
                        reason="multihost: in-process replay retained")
        elif member is not None:
            metrics.log(
                "notice", event="replay_net_fallback",
                reason="league member: in-process replay retained (a "
                       "mid-run n-step adoption mutates the window "
                       "geometry the remote shards were built with)")
        elif spec is not None:
            # game-major shard blocks pin to servers structurally (a
            # server owns shard_base..+shards, which ARE game blocks),
            # but the learner-side game-quota interleave is a host draw
            # the wire client doesn't reproduce yet
            metrics.log(
                "notice", event="replay_net_fallback",
                reason="multitask: in-process replay retained (wire "
                       "game-quota interleave is a follow-up)")
        elif frontier is not None:
            metrics.log(
                "notice", event="replay_net_fallback",
                reason="device_sampling: the HBM priority mirror needs "
                       "the in-process shard trees")
        elif cfg.serve_quantize != "off":
            metrics.log(
                "notice", event="replay_net_fallback",
                reason="serve_quantize: calibration samples the local "
                       "memory, which stays empty under a remote plane")
        else:
            from rainbow_iqn_apex_tpu.replay.net.plane import (
                RemoteReplayPlane,
            )

            rplane = RemoteReplayPlane.from_config(
                cfg, lanes, metrics=metrics,
                obs_registry=obs_run.registry,
            )
            if lfence is not None:
                # update/snapshot frames carry the learner epoch; the shard
                # servers latch the highest seen and refuse older stamps
                # (the PR-16 step fence grown an epoch dimension)
                rplane.set_learner_epoch(learner_epoch)

    frames = 0
    last_pub = 0
    restored = maybe_resume(cfg, ckpt, driver.state)
    if restored is not None:
        state, extra, _ = restored
        driver.load_state(state, extra)
        frames = int(extra.get("frames", 0))
        last_pub = driver.step
        if rplane is None:
            maybe_restore_replay(cfg, memory)
        # (remote plane: shard servers restore their own snapshots at
        # spawn, fenced by the learner's checkpoint step — nothing local)
        metrics.log("resume", step=driver.step, frames=frames)
        if lfence is not None:
            # successor version floor: the deceased learner may have
            # PUBLISHED versions above its last checkpointed
            # weights_version — start strictly above the highest version
            # any lease ever advertised, so no consumer watches the
            # successor re-issue a version number it already adopted
            peak = max(
                (lease.weight_version for lease in HeartbeatMonitor(
                    heartbeat_dir(cfg), cfg.heartbeat_timeout_s,
                ).leases().values()),
                default=-1,
            )
            if peak > driver.weights_version:
                driver.weights_version = peak
                driver.actor_weights_version = peak
            metrics.log("failover", event="restore", epoch=learner_epoch,
                        step=driver.step, version_floor=max(
                            peak, driver.weights_version))

    estimator = (
        ActorPriorityEstimator(lanes, cfg.multi_step, cfg.gamma)
        if cfg.initial_priority_from_actor
        else None
    )
    obs = env.reset()
    returns: collections.deque = collections.deque(maxlen=100)
    prefetcher: Optional[BatchPrefetcher] = None

    # Pipelined priority write-back (utils/writeback.py): step t's priorities
    # are materialized and written to the replay only while step t+K runs on
    # device, and the NaN/Inf guard reads the in-graph `finite` flag at the
    # same boundary — the steady-state learn loop issues ZERO blocking
    # device->host transfers per step (docs/PERFORMANCE.md).  The commit/
    # quarantine/drain rollback protocol is the shared RingCommitter.
    ring = WritebackRing(
        cfg.writeback_depth,
        registry=obs_run.registry,
        priorities_to_host=_local_rows if multihost else None,
        # mirror mode: retirement hands the still-on-device |TD| array to
        # frontier.update (a jitted scatter) — the priority vector never
        # crosses to host per step; reconcile() syncs the cold path at drains
        materialize_priorities=frontier is None,
        tracer=ptrace,
    )
    if rplane is not None:
        # wire write-back: the ring's retired |TD| rows route to shard
        # servers as batched update frames keyed by GLOBAL slot id — the
        # same id space memory.update_priorities routes on in-process
        _update_target = rplane.update_priorities
    elif frontier is not None and spec is not None:
        # device sampling bypasses memory.update_priorities (the |TD| stays
        # a device array retiring into the HBM mirror), so the per-game
        # learn-share counters the `games` row reports are fed from the
        # host idx vector explicitly
        def _update_target(idx, td_abs, _f=frontier.update):
            memory.note_learn_idx(idx)
            return _f(idx, td_abs)
    elif frontier is not None:
        _update_target = frontier.update
    else:
        _update_target = memory.update_priorities
    if lfence is not None:
        _unfenced_update = _update_target
        _wb_refused = [0]

        def _update_target(idx, td_abs):
            # zombie write-back fence: a superseded learner's retired |TD|
            # rows must not perturb the successor's sampling distribution.
            # One row on the first refusal (a storm is a triage signal, not
            # a log flood — docs/RUNBOOK.md), the fence counts the rest.
            if lfence.stale(learner_epoch):
                _wb_refused[0] += 1
                if _wb_refused[0] == 1:
                    metrics.log("failover", event="fenced_stale",
                                surface="writeback", epoch=learner_epoch,
                                fence_epoch=lfence.epoch)
                return None
            return _unfenced_update(idx, td_abs)
    committer = RingCommitter(
        ring,
        _update_target,
        sup,
        driver.load_snapshot,
        on_drain=(
            frontier.reconcile if frontier is not None
            # drain boundary doubles as write-back flush: every in-flight
            # update frame is acked before a snapshot/publish proceeds
            else rplane.flush_writebacks if rplane is not None
            else None
        ),
    )
    last_scalars = committer.scalars  # newest RETIRED step's host scalars
    _commit, _drain = committer.commit, committer.drain
    # replay reuse (docs/PERFORMANCE.md "Replay reuse"): one sampled batch
    # drives a fused K-pass learn dispatch, so the step counter jumps K per
    # sample — the sample trigger divides steps back into samples, cadences
    # fire on crossings (cadence_hit), and the ring still holds one entry
    # per SAMPLE (final-pass priorities), so priorities lag samples, not
    # passes
    reuse_k = driver.reuse_k
    check_reuse_cadences(cfg, "metrics_interval", "eval_interval",
                         "checkpoint_interval", "guard_snapshot_interval",
                         "weight_publish_interval")

    if multihost and cfg.pipelined_actor:
        raise ValueError("pipelined_actor is single-host only (for now)")
    # multi-host learn trigger: DETERMINISTIC and identical on every host
    # (divergent control flow around a collective deadlocks the pod).  It
    # therefore counts only fresh post-(re)start frames — len(memory) can
    # diverge across hosts when a resume restores replay on some hosts but
    # degrades to cold on one (torn snapshot) — at the cost of re-warming
    # for learn_start frames after every resume.
    frames_at_start = frames
    # device-resident stacking replaces the host FrameStacker on the actor
    # path (pipelined mode keeps the host stacker: its one-tick-lag pipe
    # would need a second in-flight device stack)
    use_dstack = cfg.device_frame_stack and not cfg.pipelined_actor
    stacker = None if use_dstack else FrameStacker(
        lanes, env.frame_shape, cfg.history_length
    )
    prev_cuts = np.zeros(lanes, bool)
    # append seam: one callable serves the pipelined and straight paths —
    # the remote plane spools lane blocks to shard servers, the local path
    # appends in-process.  With the plane active memory.append_ticks stays
    # 0, so actor trace tick ids degenerate to a constant: wire appends
    # are not causally traced yet (accepted; the learn-side links degrade
    # to unlinked spans, nothing breaks).
    _append = memory.append_batch if rplane is None else rplane.append_batch
    pending = None  # pipelined: device (actions, q) dispatched last tick
    held = None  # pipelined: completed transition awaiting its Q for append
    try:
        while frames < total_frames:
            # causal tracing: this tick's appends land on append tick
            # append_ticks+1 — sampled ticks carry act/env-step/append spans
            # under the id the learn span will link back to
            tick_tid = ptrace.maybe_trace("a", memory.append_ticks + 1)
            with ptrace.span("act", tick_tid):
                if use_dstack:
                    with obs_run.span("act"):
                        actions, q = driver.act_frames(obs, prev_cuts)
                else:
                    stacked = stacker.push(obs)
                    if multihost:
                        actions, q = driver.act_local(stacked)
                    elif cfg.pipelined_actor:
                        # Overlap: dispatch inference for THIS obs; execute
                        # the action computed from the PREVIOUS obs
                        # (one-tick behaviour lag; the first tick primes the
                        # pipe synchronously).
                        nxt = driver.act_async(stacked)
                        if pending is None:
                            pending = nxt
                        actions = np.asarray(pending[0])
                    else:
                        actions, q = driver.act(stacked)
            with ptrace.span("env_step", tick_tid):
                new_obs, rewards, terminals, truncs, ep_returns = env.step(
                    actions)
            cuts = terminals | truncs  # truncation cuts windows like a terminal
            if cfg.pipelined_actor:
                # The transition (s_t, a_t, r_t) needs Q(s_t) — that's `nxt`,
                # still computing while the envs stepped. Hold the transition
                # one tick and append it when its Q has certainly landed, so
                # actor-side priorities use the RIGHT observation's values
                # (only the behaviour policy is stale, not the estimates).
                if held is not None:
                    h_obs, h_act, h_rew, h_term, h_trunc, h_q = held
                    pri = (
                        estimator.push(np.asarray(h_q), h_act, h_rew, h_term | h_trunc)
                        if estimator
                        else None
                    )
                    # the held transition lands on THIS tick's append seq
                    # (one append per tick), so tick_tid is its id — the
                    # trace carries the pipeline's own one-tick lag
                    with ptrace.span("append", tick_tid):
                        _append(
                            h_obs, h_act, h_rew, h_term, pri, truncations=h_trunc
                        )
                held = (obs, actions, rewards, terminals, truncs, nxt[1])
                pending = nxt
            else:
                pri = estimator.push(q, actions, rewards, cuts) if estimator else None
                with ptrace.span("append", tick_tid):
                    _append(obs, actions, rewards, terminals, pri, truncations=truncs)
            if not use_dstack:
                stacker.reset_lanes(cuts)
            prev_cuts = cuts
            obs = new_obs
            frames += lanes_total  # global frames: all hosts tick in lockstep
            for r in ep_returns[~np.isnan(ep_returns)]:
                returns.append(float(r))

            if rplane is not None:
                # remote warm-up: the servers' aggregate size/sampleable
                # ride the piggyback state on every reply — no extra RPC
                warm = (rplane.size() >= learn_start
                        and rplane.sampleable())
            else:
                warm = (
                    frames - frames_at_start >= cfg.learn_start
                    if multihost
                    else len(memory) >= learn_start and memory.sampleable
                )
            if warm:
                if driver.wants_calibration():
                    # calibration from replay observation statistics: one
                    # sampled batch's stacked obs (the gate's yardstick —
                    # QuaRL calibrates post-training quantization the same
                    # way).  Only reached with serve_quantize on, so the
                    # off-mode sampler RNG stream is untouched.
                    calib = memory.sample(
                        min(cfg.quant_calib_batch, cfg.batch_size),
                        priority_beta(cfg, frames),
                    )
                    driver.set_calibration(
                        calib.obs, game=getattr(calib, "game", None))
                if rplane is not None and prefetcher is None:
                    # wire sample-ahead: the SampleClient already keeps
                    # `sample_ahead_depth` requests in flight; the shim
                    # only overlaps decode + device_put with the dispatch
                    prefetcher = rplane.make_prefetcher(
                        local_batch,
                        lambda: priority_beta(cfg, frames),
                        to_device_batch,
                        registry=obs_run.registry,
                    )
                elif frontier is not None and prefetcher is None:
                    # sample-ahead pusher: device-drawn index blocks,
                    # host-DRAM frame gather, staged device batches PUSHED
                    # into the bounded queue — the learner only pops
                    from rainbow_iqn_apex_tpu.replay.frontier import (
                        make_batch_assembler,
                    )
                    from rainbow_iqn_apex_tpu.utils.prefetch import (
                        SampleAheadPusher,
                    )

                    prefetcher = SampleAheadPusher(
                        frontier,
                        make_batch_assembler(
                            memory, to_device_batch,
                            registry=obs_run.registry,
                        ),
                        cfg.batch_size,
                        lambda: priority_beta(cfg, frames),
                        lambda: len(memory),
                        # replay reuse: one staged batch feeds K fused
                        # learn passes — the pusher shrinks its queue depth
                        # and device-side draw-ahead K-fold from reuse=
                        depth=cfg.sample_ahead_depth,
                        reuse=reuse_k,
                        registry=obs_run.registry,
                    )
                elif cfg.prefetch_depth > 0 and prefetcher is None:
                    if multihost:
                        # overlap the host-side local sample/assembly with
                        # the device step; the collective-bearing
                        # learn_local stays on the main thread
                        prefetcher = BatchPrefetcher(
                            lambda: (
                                (s := memory.sample(
                                    local_batch, priority_beta(cfg, frames)
                                )).idx,
                                s,
                            ),
                            depth=cfg.prefetch_depth,
                            device_put=False,
                            registry=obs_run.registry,
                        )
                    else:
                        prefetcher = make_replay_prefetcher(
                            memory, cfg, lambda: priority_beta(cfg, frames),
                            registry=obs_run.registry,
                        )
                steps_due = (frames // cfg.frames_per_learn
                             - driver.step // reuse_k)
                for _ in range(max(steps_due, 0)):
                    if sup.snapshot_due(driver.step):
                        # drain BEFORE capturing: the snapshot must never
                        # contain a step whose finiteness is still in flight
                        # (it is the rollback target)
                        if not _drain():
                            continue
                        sup.snapshot_if_due(
                            driver.step,
                            lambda: (host_state(driver.state), driver.key),
                        )
                    # causal tracing: the step this dispatch creates; its
                    # span links back to the sampled append ticks its batch
                    # rows came from (env-step -> learn flow arrows)
                    ltid = ptrace.maybe_trace("l", driver.step + 1)
                    if multihost:
                        # local sub-batch in; the global batch assembles
                        # across hosts inside, IS weights are re-derived
                        # globally, and the ring extracts this host's local
                        # priority rows at retirement
                        with ptrace.span("gather", ltid):
                            if prefetcher is not None:
                                idx, sample = prefetcher.get()
                            else:
                                sample = memory.sample(local_batch, priority_beta(cfg, frames))
                                idx = sample.idx
                        links = ptrace.link_ids(
                            "a", memory.trace_ids(idx)) if ltid else ()
                        with ptrace.span("learn_step", ltid, links=links,
                                         step=driver.step + 1):
                            with obs_run.span("learn_step"):
                                info = driver.learn_local(
                                    sup.poison_maybe(sample),
                                    global_size=len(memory) * nproc,
                                    beta=priority_beta(cfg, frames),
                                )
                    elif prefetcher is not None:
                        with ptrace.span("gather", ltid):
                            idx, batch = prefetcher.get()
                        # slot stamps are read at DISPATCH, not at the
                        # worker's sample: a slot the ring cursor lapped in
                        # between (<= lanes*depth/capacity odds per batch)
                        # links one tick late — accepted for sampled
                        # telemetry rather than threading stamps through
                        # every prefetcher payload
                        links = ptrace.link_ids(
                            "a", memory.trace_ids(idx)) if ltid else ()
                        with ptrace.span("learn_step", ltid, links=links,
                                         step=driver.step + 1):
                            with obs_run.span("learn_step"):
                                info = driver.learn_batch(sup.poison_maybe(batch))
                    else:
                        with ptrace.span("replay_sample", ltid):
                            with obs_run.span("replay_sample"):
                                sample = memory.sample(
                                    local_batch, priority_beta(cfg, frames)
                                )
                        idx = sample.idx
                        links = ptrace.link_ids(
                            "a", memory.trace_ids(idx)) if ltid else ()
                        with ptrace.span("learn_step", ltid, links=links,
                                         step=driver.step + 1):
                            with obs_run.span("learn_step"):
                                info = driver.learn(sup.poison_maybe(sample))
                    sup.maybe_stall()
                    # Dispatch-only hot path: info stays on device; the ring
                    # retires step t-K (write-back + deferred NaN guard)
                    # while step t executes.  The guard decision is still
                    # identical on every host — the loss is all-reduced, so
                    # the in-graph finite flag agrees and rollback stays
                    # lockstep (no divergent control flow around a
                    # collective).
                    if not _commit(ring.push(driver.step, idx, info)):
                        continue
                    step = driver.step
                    obs_run.after_learn_step(step, units=reuse_k)
                    if step - last_pub >= cfg.weight_publish_interval:
                        # ring boundary: actors must never adopt params with
                        # an unverified step in their history, so everything
                        # in flight retires (and may roll us back) first
                        if not _drain():
                            continue
                        with obs_run.span("publish_weights"):
                            version = driver.publish_weights()
                        last_pub = step
                        obs_run.registry.gauge(
                            "weights_version", "learner"
                        ).set(version)
                        if heartbeat is not None:
                            heartbeat.set_weight_version(version)
                        if member is not None:
                            if (lfence is not None
                                    and lfence.stale(learner_epoch)):
                                # zombie league fence: a superseded member
                                # incarnation must not clobber the
                                # successor's outbox delta chain
                                metrics.log(
                                    "failover", event="fenced_stale",
                                    surface="league", epoch=learner_epoch,
                                    fence_epoch=lfence.epoch)
                            else:
                                # league outbox publish (the int8-delta
                                # chain other members adopt from) rides the
                                # same drained boundary as the actor
                                # broadcast
                                with hostsync.sanctioned():
                                    member.publish(
                                        host_state(driver.state).params,
                                        step=step)
                    if (member is not None
                            and cadence_hit(step, cfg.metrics_interval,
                                            reuse_k)
                            and member.pending()):
                        # exploit adoption at a SAFE drain boundary: every
                        # in-flight step retires (and may roll back) before
                        # the copied weights land; adopt_params republishes
                        # so the actor lanes swap atomically with the
                        # learner
                        if not _drain():
                            continue
                        with hostsync.sanctioned():
                            adopted = member.try_adopt(
                                step, driver.adopt_params, retune=None,
                                max_n_step=memory.max_n_step)
                        if adopted is not None:
                            genome = member.genome
                            driver.retune(
                                learning_rate=genome.learning_rate)
                            memory.set_n_step(genome.n_step)
                            memory.set_priority_exponent(
                                genome.priority_exponent)
                            if estimator is not None:
                                # actor-side priority windows are sized by
                                # n-step: restart the estimator's deques
                                # (it re-primes within n ticks; fresh
                                # appends take the max-priority default
                                # meanwhile, the Ape-X cold-start rule)
                                estimator = ActorPriorityEstimator(
                                    lanes, genome.n_step, cfg.gamma)
                            last_pub = step  # adopt_params republished
                            if heartbeat is not None:
                                heartbeat.set_weight_version(
                                    driver.weights_version)
                    if cadence_hit(step, cfg.metrics_interval, reuse_k):
                        fence.observe(
                            driver.actor_weights_version,
                            driver.weights_version,
                            step=step,
                        )
                        # scalars come from the newest RETIRED step (<= K
                        # behind) — the metric cadence reads host floats the
                        # ring already materialized, never the device queue
                        metrics.log(
                            "learn",
                            step=step,
                            frames=frames,
                            fps=metrics.fps(frames),
                            loss=last_scalars.get("loss", float("nan")),
                            q_mean=last_scalars.get("q_mean", float("nan")),
                            mean_return=float(np.mean(returns)) if returns else float("nan"),
                            staleness=step - last_pub,
                            **reuse_learn_row(reuse_k, last_scalars),
                        )
                        obs_run.periodic(
                            step,
                            frames,
                            replay_size=(
                                rplane.size() if rplane is not None
                                else len(memory)
                            ),
                            # survivors-aware occupancy maintained by
                            # ShardedReplay._observe on this same registry —
                            # recomputing it here would double-count dead
                            # shards in the denominator
                            replay_occupancy=round(
                                obs_run.registry.gauge(
                                    "replay_occupancy", "replay"
                                ).get(), 4,
                            ),
                            weight_staleness=step - last_pub,
                            weights_version=driver.weights_version,
                            weight_version_lag=fence.lag,
                            **pipeline_gauges(
                                ring, obs_run.registry, frontier,
                                reuse=reuse_health(reuse_k, last_scalars),
                            ),
                        )
                        if spec is not None:
                            # per-game breakdown (docs/MULTITASK.md): learn
                            # share, replay occupancy, latest eval score,
                            # human-normalized aggregate — the row obs_report
                            # `games:` and relay_watch key on
                            metrics.log(
                                "games", step=step, frames=frames,
                                schedule=cfg.multitask_schedule,
                                **games_obs.row(
                                    learn_shares=memory.learn_shares(),
                                    learn_rows=memory.learn_rows_by_game,
                                    sampled_rows=memory.sampled_rows_by_game,
                                    game_sizes=memory.game_sizes(),
                                    game_occupancy=memory.game_occupancy(),
                                    dead_games=memory.dead_games(),
                                ),
                            )
                        # lag-attribution row (obs/pipeline_trace.py):
                        # sample age / retirement / publish->adopt
                        # percentiles, RunHealth folds budget breaches.
                        # Reuse accounting: K > 1 multiplies learn_steps/s
                        # at a fixed publish-interval-in-steps, so the WALL
                        # publish cadence — and with it the publish->adopt
                        # budget — shrinks ~K-fold; the row carries
                        # replay_ratio so a budget shift reads as the knob,
                        # not a regression.
                        ptrace.emit_lag_row(
                            step,
                            **({} if reuse_k == 1
                               else {"replay_ratio": reuse_k}),
                        )
                        # the zombie's wake-up path: claim markers are
                        # plain files, visible to a process that was
                        # paused through the whole takeover the moment it
                        # resumes.  A latched successor epoch is TERMINAL:
                        # stop training (the per-surface fences would
                        # refuse everything anyway), never checkpoint
                        # again, and fall through to the zombie return.
                        if _zombie_detected(step):
                            zombie = True
                            break
                        if monitor is not None:
                            # a preempted host stops heartbeating; the
                            # host_dead row is the external supervisor's
                            # restart/reshard signal — a hung collective
                            # would otherwise wedge this loop silently.
                            # poll() reports BOTH edges once per lease
                            # epoch: the revival side is what lets an
                            # external controller readmit the host's shard
                            # instead of treating recovery as noise.
                            dead, alive = monitor.poll()
                            for lease in dead:
                                # dead_host, not host: the envelope's `host`
                                # key is the EMITTING process index
                                metrics.log(
                                    "fault", event="host_dead",
                                    dead_host=lease.host, epoch=lease.epoch,
                                    step=step, frames=frames,
                                )
                            for lease in alive:
                                metrics.log(
                                    "host_alive", alive_host=lease.host,
                                    epoch=lease.epoch, step=step,
                                    frames=frames,
                                )
                        if rplane is not None:
                            # replay-plane lifecycle: lease edges map to
                            # drop/readmit on the sampler, plus the
                            # periodic `replay_net` stats row
                            rplane.poll(step)
                    if cadence_hit(step, cfg.eval_interval, reuse_k):
                        # the drain runs on EVERY host (the cadence is a
                        # function of the lockstep step counter) so a
                        # rollback here stays lockstep; only the eval
                        # itself is main-host work
                        if not _drain():  # evaluate only verified params
                            continue
                        if is_main and spec is not None:
                            _eval_multigame(
                                cfg, spec, driver, metrics, step, games_obs)
                        elif is_main:
                            metrics.log(
                                "eval", step=step,
                                **_eval_learner(cfg, env, driver),
                            )
                    if cadence_hit(step, cfg.checkpoint_interval, reuse_k):
                        # re-check the fence at the WRITE itself: the
                        # checkpoint cadence need not share a step with the
                        # metrics cadence, and a zombie's force=True save
                        # into the successor's live Orbax dir is the one
                        # fenced surface a refusal cannot undo after the
                        # fact
                        if _zombie_detected(step):
                            zombie = True
                            break
                        if not _drain():  # checkpoint only verified params
                            continue
                        # every host calls save — Orbax treats it as a
                        # collective under jax.distributed (primary host
                        # writes, the rest join its barrier); a p0-only call
                        # would hang the pod at the next sync point.  The
                        # retry wrapper's decisions are deterministic, so
                        # hosts retry in lockstep too.
                        sup.save_checkpoint(
                            ckpt, step, host_state(driver.state),
                            # epoch in the extras: a successor's epoch-k+1
                            # checkpoint outranks the deceased epoch-k
                            # learner's in-flight save even when the
                            # zombie's step counter ran ahead
                            # (Checkpointer._steps_by_epoch); 0 is never
                            # stamped so the off path stays byte-identical
                            {"frames": frames, "weights_version": driver.weights_version,
                             **({"learner_epoch": learner_epoch}
                                if learner_epoch > 0 else {}),
                             **rng_extra(driver.key)},
                        )
                        if rplane is None:
                            sup.save_replay(cfg, memory)  # per-host shard
                        else:
                            # server-side snapshots, fenced by this step so
                            # a rewound learner can't re-trigger older ones
                            rplane.request_snapshot(step)
            if zombie:
                break  # superseded: stop acting/appending too, not just learning
        # end of run: the still-in-flight tail retires (write-back + guard)
        # before the final eval/checkpoint read the state
        _drain()
    finally:
        if prefetcher is not None:
            prefetcher.close()
        if rplane is not None:
            rplane.close()
        sup.close()
        obs_run.close(driver.step, frames)
        if heartbeat is not None:
            heartbeat.stop()
        if league_hb is not None:
            league_hb.stop()
    # last fence look before the final writes: a run that ended NORMALLY
    # while a successor was claiming (fence latched between the last cadence
    # and loop exit) must not push a final checkpoint/replay snapshot into
    # the successor's live run dir either
    if not zombie and _zombie_detected(driver.step):
        zombie = True
    if zombie:
        # A superseded incarnation stops touching the run dir HERE: no
        # final eval (its rows would read as authoritative), no final
        # checkpoint or replay snapshot (the successor's CheckpointManager
        # owns the directory now).  The terminal failover row already
        # landed; wait() only joins this process's in-flight save threads.
        ckpt.wait()
        metrics.close()
        return {
            "frames": frames,
            "learn_steps": driver.step,
            "lanes": lanes_total,
            "train_return_mean": (
                float(np.mean(returns)) if returns else float("nan")),
            "rollbacks": sup.rollbacks,
            "stalls": sup.stalls,
            "io_faults": sup.io_faults,
            "zombie_exit": True,
        }
    if is_main and spec is not None:
        final_eval = _eval_multigame(
            cfg, spec, driver, metrics, driver.step, games_obs)
    elif is_main:
        final_eval = _eval_learner(cfg, env, driver)
        metrics.log("eval", step=driver.step, **final_eval)
    else:
        final_eval = {}
    sup.save_checkpoint(
        ckpt, driver.step, host_state(driver.state),
        {"frames": frames, "weights_version": driver.weights_version,
         **({"learner_epoch": learner_epoch} if learner_epoch > 0 else {}),
         **rng_extra(driver.key)}, critical=True,
    )
    if frontier is not None:
        # the final drain may have been skipped by a rollback: catch the
        # cold-path trees up before they are persisted
        frontier.reconcile()
    if rplane is None:
        sup.save_replay(cfg, memory, critical=True)
    else:
        rplane.request_snapshot(driver.step)
    ckpt.wait()
    metrics.close()
    return {
        "frames": frames,
        "learn_steps": driver.step,
        "lanes": lanes_total,
        "train_return_mean": float(np.mean(returns)) if returns else float("nan"),
        "rollbacks": sup.rollbacks,
        "stalls": sup.stalls,
        "io_faults": sup.io_faults,
        **{f"eval_{k}": v for k, v in final_eval.items()},
    }

