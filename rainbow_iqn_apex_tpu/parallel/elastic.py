"""Elastic self-healing fleet layer: close the detect -> degrade -> HEAL loop.

Ape-X throughput scales linearly with actor count (arXiv:1803.00933), so a
permanently-lost actor host is a permanent throughput tax.  PR 2 made host
loss *survivable* (heartbeat staleness -> ``host_dead`` -> survivors-only
replay sampling) and PR 3 made it *visible* (RunHealth degraded), but the
fleet never recovered: dropped shards were one-way, dead roles stayed dead,
and actors kept acting on unboundedly stale weights — which IMPACT
(arXiv:1912.00167) shows corrupts learning silently long before anything
crashes.  This module adds the missing half:

- **Role leases** (`HeartbeatWriter`/`HeartbeatMonitor`, grown from PR 2's
  heartbeats): every heartbeat file is now a lease row carrying
  (role, shard, lease epoch, weight_version).  The monitor reports BOTH
  edges — ``host_dead`` when a lease expires and ``host_alive`` when a
  host beats again — each fired once per lease epoch, so a respawned
  incarnation (epoch+1) is a new event while a flapping stale file is not.
- **Weight mailbox + staleness fence** (`WeightMailbox`, `StalenessFence`):
  the learner publishes a monotonically increasing weight version; actors
  track ``weight_version_lag`` and past ``cfg.max_weight_lag`` publishes
  they PAUSE acting (shed frames, emit ``actor_fenced`` rows) instead of
  polluting replay with off-policy-beyond-budget experience.
- **Respawn supervision** (`RoleSupervisor`): dead actor processes are
  restarted under the shared `RetryPolicy` backoff and `FailureBudget` —
  bounded restarts with a fresh lease epoch per incarnation, then permanent
  eviction with an ``actor_evicted`` fault row (the `train_aborted` of the
  fleet layer).

The readmission half lives in `ShardedReplay.readmit_shard` (epoch-fenced;
parallel/sharded_replay.py); `scripts/chaos_soak.py` drives the whole loop
through a seeded kill/revive schedule.  Everything here is deliberately
jax-free so respawned actor processes pay no device-runtime import tax.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from rainbow_iqn_apex_tpu.utils import faults


def heartbeat_dir(cfg) -> str:
    return os.path.join(cfg.results_dir, cfg.run_id, "heartbeats")


def next_lease_epoch(directory: str, process_id: int) -> int:
    """Claim this host's next incarnation epoch.  Every process START —
    first launch, scheduler restart, crash-loop relaunch — gets a bumped
    epoch, which is what makes the monitor's once-per-epoch transition
    dedupe see a relaunched incarnation as a NEW death/revival instead of
    suppressing it, and what epoch-fences the dead incarnation's writes.

    The claim is one empty O_EXCL marker file per epoch (``h<i>.e<k>``),
    NOT a read-modify-write counter: a double-launch of the same host id
    (scheduler races its own zombie — exactly the split-brain epoch fencing
    exists for) must end up with two DIFFERENT epochs, and O_EXCL is the
    one primitive that guarantees it.  Markers are a few bytes each and
    bounded by the restart count.  A supervisor that assigns epochs
    explicitly (RoleSupervisor) does not need this; it exists for
    self-managed launches (launch_apex.sh, `--resume auto` under an
    external scheduler)."""
    os.makedirs(directory, exist_ok=True)
    epoch = 0
    while True:
        try:
            fd = os.open(
                os.path.join(directory, f"h{process_id}.e{epoch}"),
                os.O_CREAT | os.O_EXCL | os.O_WRONLY,
            )
            os.close(fd)
            return epoch
        except FileExistsError:
            epoch += 1


# ----------------------------------------------------------- role epoch claims
def claim_role_epoch(directory: str, role: str, epoch: int) -> bool:
    """Claim ``role`` at ``epoch``; exactly ONE of N racing claimants wins.

    The primitive behind learner failover (parallel/failover.py): two hot
    standbys that both watched the learner's lease expire race to create the
    SAME ``<role>.e<epoch>`` marker with O_CREAT|O_EXCL — the filesystem
    picks one winner atomically, the loser re-arms.  Unlike
    ``next_lease_epoch`` the marker is keyed by ROLE, not host id, because
    the racers are different processes with different pids claiming one
    logical role.  Returns True when THIS caller created the marker."""
    os.makedirs(directory, exist_ok=True)
    try:
        fd = os.open(
            os.path.join(directory, f"{role}.e{int(epoch)}"),
            os.O_CREAT | os.O_EXCL | os.O_WRONLY,
        )
        os.close(fd)
        return True
    except FileExistsError:
        return False


def latest_role_epoch(directory: str, role: str) -> int:
    """Highest epoch ever claimed for ``role`` (-1 when none): the floor a
    standby must claim ABOVE — claiming ``latest + 1`` can only lose to a
    sibling standby (re-arm and re-read), never to a dead incarnation."""
    prefix = f"{role}.e"
    best = -1
    try:
        names = os.listdir(directory)
    except FileNotFoundError:
        return best
    for name in names:
        if name.startswith(prefix):
            try:
                best = max(best, int(name[len(prefix):]))
            except ValueError:
                continue
    return best


class StaleEpochError(ValueError):
    """A publish/write-back stamped with a superseded learner epoch was
    refused — the zombie fence (docs/RESILIENCE.md "zombie learner")."""


class EpochFence:
    """Monotone learner-epoch latch: the one rule every fenced surface
    (weight publish, priority write-back, replay-net snapshot, league
    outbox) shares.  ``observe`` latches the highest epoch ever seen (from
    leases, mailbox rows, claim markers); ``stale(epoch)`` answers whether a
    write stamped ``epoch`` names a superseded incarnation and counts the
    refusal.  With failover off no epoch above 0 ever exists, so ``stale``
    is identically False and the fenced paths are bitwise the pre-failover
    behaviour."""

    def __init__(self, epoch: int = 0):
        self._epoch = int(epoch)
        self._lock = threading.Lock()
        self.refusals = 0

    @property
    def epoch(self) -> int:
        with self._lock:
            return self._epoch

    def observe(self, epoch: int) -> int:
        """Latch ``max(current, epoch)``; returns the latched epoch."""
        with self._lock:
            self._epoch = max(self._epoch, int(epoch))
            return self._epoch

    def stale(self, epoch: int) -> bool:
        """True — and counted — when ``epoch`` is superseded."""
        with self._lock:
            if int(epoch) < self._epoch:
                self.refusals += 1
                return True
            return False


# ------------------------------------------------------------- lease writing
class HeartbeatWriter:
    """Daemon thread re-writing this host's lease file every ``interval_s``.

    The file doubles as PR 2's liveness heartbeat and this PR's role lease:
    the payload carries (role, shard, lease epoch, weight_version) so the
    monitor can tell a respawned incarnation (new epoch) from a flapping
    file, and an external observer can see what the host was FOR.  Writes
    are atomic (tmp + rename) so a reader never sees a torn JSON.  The
    ``heartbeat_loss`` fault point suppresses writes (a preempted host,
    manufactured); ``lease_lost`` does the same for a live process whose
    renewals stop (a zombie incarnation — the split-brain shape epoch
    fencing exists for)."""

    def __init__(self, directory: str, process_id: int, interval_s: float,
                 injector: Optional[faults.FaultInjector] = None,
                 role: str = "host", shard: Optional[int] = None,
                 epoch: int = 0,
                 payload_fn: Optional[Callable[[], Dict]] = None):
        self.directory = directory
        self.process_id = int(process_id)
        self.interval_s = float(interval_s)
        self.injector = injector if injector is not None else faults.get()
        self.path = os.path.join(directory, f"h{process_id}.json")
        self.payload: Dict = {"role": role, "epoch": int(epoch)}
        if shard is not None:
            self.payload["shard"] = int(shard)
        # the multi-game lease payload field (`game`, read back as
        # Lease.game) rides update_payload like every other contract field
        # dynamic lease payload (serving fleet): merged into every renewal so
        # fast-moving fields (queue_depth, weights_version) ride the lease
        # without the owner calling update_payload on its own hot path
        self.payload_fn = payload_fn
        self.beats = 0
        self.suppressed = 0
        # payload writers (adopt/rollout threads) race the beat thread's
        # read; an unguarded dict resize mid-unpack would raise past the
        # loop's OSError net and silently kill the heartbeat — a healthy
        # engine would then be evicted on a phantom lease expiry
        self._payload_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def set_weight_version(self, version: int) -> None:
        """Stamp the weight version this host currently acts with; rides in
        every subsequent lease renewal (external staleness monitoring)."""
        with self._payload_lock:
            self.payload["weight_version"] = int(version)

    def update_payload(self, **fields: Any) -> None:
        """Merge static fields (lanes, buckets, ...) into every renewal."""
        with self._payload_lock:
            self.payload.update(fields)

    def beat(self) -> None:
        """One lease renewal (also usable inline, without the thread)."""
        if self.injector.enabled:
            hb = self.injector.fire("heartbeat_loss")
            ll = self.injector.fire("lease_lost")
            if hb or ll:
                with self._payload_lock:
                    self.suppressed += 1
                return
        os.makedirs(self.directory, exist_ok=True)
        dynamic: Dict = {}
        if self.payload_fn is not None:
            try:
                dynamic = dict(self.payload_fn())
            except Exception:
                pass  # a flaky gauge read must not suppress the renewal itself
        with self._payload_lock:
            static = dict(self.payload)
        row = {
            "process_id": self.process_id,
            "t_mono": time.monotonic(),
            "t_wall": time.time(),
            **static,
            **dynamic,
        }
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(row, f)
        os.replace(tmp, self.path)
        with self._payload_lock:
            self.beats += 1

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.beat()
            except OSError:
                pass  # a flaky FS write is itself a missed beat; keep going
            self._stop.wait(self.interval_s)

    def start(self) -> "HeartbeatWriter":
        if self._thread is None:
            self.beat()  # first beat synchronously: exists before any check
            self._thread = threading.Thread(
                target=self._run, name="heartbeat-writer", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


@dataclasses.dataclass(frozen=True)
class Lease:
    """One host's lease as last observed on disk."""

    host: int
    age_s: float
    fresh: bool  # age <= the monitor's timeout
    role: str = "host"
    shard: Optional[int] = None
    epoch: int = 0
    weight_version: int = -1
    fenced: bool = False  # the host's staleness fence is currently closed
    payload_ok: bool = True  # False: mtime was readable, the JSON was not
    # serving-fleet payload (role "engine", serving/fleet/registry.py): the
    # router discovers capacity and load through the SAME lease machinery
    # that heals training hosts — no second discovery protocol
    lanes: int = 0  # engine mesh width (dispatch weight denominator)
    buckets: Tuple[int, ...] = ()  # padded batch sizes the engine compiled
    queue_depth: int = -1  # engine request-queue depth at the last renewal
    # cross-host serving plane (serving/net/): where this engine's
    # TransportServer listens.  "" / 0 = in-process only — the registry
    # attaches no remote transport and the engine is visible-but-unroutable
    # from other hosts, exactly the pre-net behaviour
    addr: str = ""
    port: int = 0
    # multi-game payload (multitask/): the game (or comma-joined game set)
    # this host's lanes are pinned to — RoleSupervisor respawn decisions and
    # fence monitors stay game-aware without a second discovery channel
    game: Optional[str] = None
    # league payload (league/; docs/LEAGUE.md): which population member this
    # host trains and at which exploit generation — the league controller
    # reads PBT state straight off the lease it already watches, no second
    # discovery channel (same rationale as `game`)
    member: Optional[int] = None
    generation: int = -1
    # learner-failover payload (parallel/failover.py): the learner-role
    # epoch this incarnation trains under.  Distinct from ``epoch`` (the
    # HOST incarnation counter): a learner host may respawn many times
    # (epoch climbs) while the learner ROLE stays at one learner_epoch until
    # a standby takes over.  Standbys fence takeover claims on it.
    learner_epoch: int = 0
    # live fleet telemetry payload (obs/net/): where the obs collector's
    # aggregated /metrics + /fleetz HTTP endpoint listens — dashboards
    # (scripts/obs_top.py) discover it through the same lease the relays
    # dial, no second discovery channel
    http_port: int = 0


# ---------------------------------------------------------- lease monitoring
class HeartbeatMonitor:
    """Scan peer lease files; report dead AND revived hosts, edge-triggered.

    Staleness is judged by file mtime (monotone-ish on one filesystem and
    immune to clock skew between hosts writing wall-clock payloads).  A host
    with NO file yet is not dead — it may simply not have started; only a
    file that existed and stopped updating is a death signal.

    Transition dedupe fires **once per lease epoch**: a host reported dead
    stays reported until it is observed ALIVE (a fresh beat) — NOT until its
    file merely becomes unobservable.  The previous implementation forgot a
    reported host the moment its file vanished (eviction cleanup, a torn
    read racing a rename), so a lingering stale file re-emitted ``host_dead``
    on every poll after such a gap; regression-tested in
    tests/test_multihost.py.  A stale file carrying a HIGHER epoch than the
    one reported is a new incarnation that died before it was ever seen
    fresh — that is a fresh death and fires again.
    """

    def __init__(self, directory: str, timeout_s: float,
                 self_id: Optional[int] = None,
                 skew_tolerance_s: float = 0.0):
        self.directory = directory
        self.timeout_s = float(timeout_s)
        # extra freshness grace absorbing reader-vs-writer clock skew: mtime
        # is stamped by the WRITER's clock (NFS and friends), age by the
        # READER's, so a reader running ahead inflates every age and can
        # false-evict a healthy host (cfg.lease_skew_tolerance_s).  The
        # grace widens only the fresh/dead boundary — reported ages stay raw
        self.skew_tolerance_s = float(skew_tolerance_s)
        self.self_id = self_id
        # host -> lease epoch at which its death was reported; entries are
        # removed ONLY by an observed fresh beat (the bugfix above)
        self._dead_epochs: Dict[int, int] = {}

    def leases(self) -> Dict[int, Lease]:
        """host id -> Lease for every readable lease file."""
        out: Dict[int, Lease] = {}
        try:
            names = os.listdir(self.directory)
        except FileNotFoundError:
            return out
        now = time.time()
        for name in names:
            if not (name.startswith("h") and name.endswith(".json")):
                continue
            path = os.path.join(self.directory, name)
            try:
                hid = int(name[1:-5])
                age = now - os.path.getmtime(path)
            except (ValueError, OSError):
                continue  # torn tmp file or a peer mid-rename
            payload: Dict = {}
            payload_ok = True
            try:  # payload is best-effort: mtime alone decides liveness
                with open(path) as f:
                    payload = json.load(f)
            except (OSError, ValueError):
                payload_ok = False
            shard = payload.get("shard")
            out[hid] = Lease(
                host=hid,
                age_s=age,
                fresh=age <= self.timeout_s + self.skew_tolerance_s,
                role=str(payload.get("role", "host")),
                shard=None if shard is None else int(shard),
                epoch=int(payload.get("epoch", 0) or 0),
                weight_version=int(payload.get("weight_version", -1)),
                fenced=bool(payload.get("fenced", False)),
                payload_ok=payload_ok,
                lanes=int(payload.get("lanes", 0) or 0),
                buckets=tuple(int(b) for b in payload.get("buckets") or ()),
                queue_depth=int(payload.get("queue_depth", -1)),
                game=payload.get("game"),
                member=(None if payload.get("member") is None
                        else int(payload["member"])),
                generation=int(payload.get("generation", -1)),
                learner_epoch=int(payload.get("learner_epoch", 0) or 0),
                addr=str(payload.get("addr", "") or ""),
                port=int(payload.get("port", 0) or 0),
                http_port=int(payload.get("http_port", 0) or 0),
            )
        return out

    def ages(self) -> Dict[int, float]:
        """host id -> seconds since its lease file was last written."""
        return {hid: lease.age_s for hid, lease in self.leases().items()}

    def check(self) -> List[int]:
        """All hosts currently considered dead (stale past timeout)."""
        return sorted(
            hid
            for hid, lease in self.leases().items()
            if not lease.fresh and hid != self.self_id
        )

    def poll(self) -> Tuple[List[Lease], List[Lease]]:
        """(newly_dead, newly_alive) lease lists — the edges since the last
        poll, each fired once per (host, epoch)."""
        newly_dead: List[Lease] = []
        newly_alive: List[Lease] = []
        for hid, lease in sorted(self.leases().items()):
            if hid == self.self_id:
                continue
            if lease.fresh:
                # the alive edge's epoch is LOAD-BEARING (readmission fences
                # on it): if the payload read raced the writer's rename,
                # defer the edge to the next poll rather than hand the
                # controller a default epoch 0 — the file is being actively
                # rewritten every interval, so the retry is imminent.  The
                # DEATH edge below deliberately does not defer: a torn final
                # write from a dying host may never become readable, and a
                # conservative epoch-0 death report (re-fired if a real
                # higher epoch surfaces later) beats missing the death.
                if not lease.payload_ok:
                    continue
                if hid in self._dead_epochs:
                    del self._dead_epochs[hid]
                    newly_alive.append(lease)
            else:
                reported = self._dead_epochs.get(hid)
                if reported is None or lease.epoch > reported:
                    self._dead_epochs[hid] = lease.epoch
                    newly_dead.append(lease)
        return newly_dead, newly_alive

    def newly_dead(self) -> List[int]:
        """Hosts that died since the last poll (compat shim over ``poll``;
        callers that also want the revival edge use ``poll`` directly)."""
        dead, _ = self.poll()
        return [lease.host for lease in dead]


# ------------------------------------------------------------ weight mailbox
class WeightMailbox:
    """Version-stamped weight publication for out-of-process actors.

    The in-process apex loop broadcasts params over the mesh; processes
    outside the SPMD program (soak actors, external fleets) instead watch
    this tiny JSON file.  ``publish`` is atomic (tmp + rename) so a reader
    never sees a torn row; the version is monotonically increasing, which is
    what makes the staleness fence's lag arithmetic meaningful.

    **Quantized delta payloads** (``publish_params``, utils/quantize.py):
    the mailbox can additionally carry the weights themselves — a periodic
    full base snapshot plus int8 per-tensor-scaled deltas against the last
    reconstruction, one ``.npz`` per publish next to the JSON row.  The row
    records the chain-from-base, so a late joiner (or a subscriber that
    missed a delta) replays base+deltas and lands **bit-exact** on the
    publisher's reconstruction; `MailboxSubscriber` applies only the new
    tail when it is already in sync.  Payload files older than the
    previous base are pruned (laggards one base behind still resync).
    ``publish_compression="off"`` callers simply never call
    ``publish_params`` — ``publish`` is byte-for-byte the PR-4 behaviour."""

    def __init__(self, path: str, base_interval: int = 10,
                 compression: str = "int8_delta", host: int = 0):
        self.path = path
        self.base_interval = int(base_interval)
        self.compression = compression
        # stamped into every row as pub_host: subscribers rebuild the
        # publisher's "w<host>-<version>" trace id from it, which is what
        # lets trace_export draw the publish->adopt flow across processes
        self.host = int(host)
        self._encoder = None  # created on first publish_params
        self._files: Dict[int, str] = {}  # version -> payload file

    def publish(self, version: int, step: int = 0,
                learner_epoch: Optional[int] = None, **extra: Any) -> None:
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        if learner_epoch is not None:
            # the authoritative cross-process zombie fence: the row ON DISK
            # carries the epoch that wrote it, and a publish stamped with an
            # OLDER one (a paused-not-dead learner waking after takeover)
            # is refused before anything is written.  None (the default)
            # keeps the pre-failover path byte-for-byte.
            held = int((self.read() or {}).get("learner_epoch", 0) or 0)
            if held > int(learner_epoch):
                raise StaleEpochError(
                    f"mailbox publish from learner epoch {learner_epoch} "
                    f"refused: epoch {held} already published")
            extra = {"learner_epoch": int(learner_epoch), **extra}
        row = {"version": int(version), "step": int(step),
               "ts": round(time.time(), 3), "pub_host": self.host, **extra}
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(row, f)
        os.replace(tmp, self.path)

    def read(self) -> Optional[Dict[str, Any]]:
        try:
            with open(self.path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None  # unpublished yet, or a reader racing the rename

    # ------------------------------------------------ quantized delta payloads
    def _payload_dir(self) -> str:
        return os.path.splitext(self.path)[0] + "_payload"

    def publish_params(self, params: Any, version: int, step: int = 0,
                       learner_epoch: Optional[int] = None,
                       **extra: Any) -> Dict[str, Any]:
        """Publish the actual weights as a delta-compressed payload plus the
        version row.  Monotone: a backward/duplicate version raises (the
        mailbox mirror of FleetRollout's refused_backward), and a
        ``learner_epoch`` older than the one already on disk raises
        `StaleEpochError` BEFORE any payload file is written (the zombie
        fence — a superseded learner must not clobber the successor's delta
        chain).  Returns the row written, with ``bytes`` = the packet's
        logical wire size."""
        from rainbow_iqn_apex_tpu.utils import quantize as quantize_mod

        if learner_epoch is not None:
            held = int((self.read() or {}).get("learner_epoch", 0) or 0)
            if held > int(learner_epoch):
                raise StaleEpochError(
                    f"mailbox params publish from learner epoch "
                    f"{learner_epoch} refused: epoch {held} already "
                    "published")
        if self._encoder is None:
            if self.compression == "int8_delta":
                self._encoder = quantize_mod.DeltaEncoder(self.base_interval)
            else:  # "off": full fp32 snapshots, every publish its own base
                self._encoder = quantize_mod.DeltaEncoder(1)
        if int(version) <= self._encoder.version:
            raise ValueError(
                f"mailbox publishes are monotone: version {version} <= "
                f"published {self._encoder.version}")
        directory = self._payload_dir()
        os.makedirs(directory, exist_ok=True)
        packet = self._encoder.encode(params, int(version))
        fname = f"w_v{int(version)}_{packet.kind}.npz"
        quantize_mod.save_packet(packet, os.path.join(directory, fname))
        self._files[int(version)] = fname
        chain_versions = [p.version for p in self._encoder.chain()]
        # a fresh base starts a new chain; everything before it is
        # unreachable by any resync (a laggard replays the NEW chain, whose
        # base resets its state), so the old chain's files are pruned
        for v in [v for v in self._files if v < chain_versions[0]]:
            try:
                os.unlink(os.path.join(directory, self._files.pop(v)))
            except OSError:
                self._files.pop(v, None)
        self.publish(
            version, step=step, learner_epoch=learner_epoch,
            payload_kind=packet.kind,
            payload_file=fname,
            base_version=packet.base_version,
            chain=[[v, self._files[v]] for v in chain_versions
                   if v in self._files],
            bytes=packet.nbytes(),
            compression=self.compression,
            **extra,
        )
        return self.read() or {}

    def read_params(self) -> Optional[Any]:
        """Stateless full reconstruction (a fresh late joiner): replay the
        row's chain-from-base.  None when nothing (or no payload) is
        published or the chain is unreadable — callers retry at the next
        publish, exactly like a torn `read`."""
        from rainbow_iqn_apex_tpu.utils import quantize as quantize_mod

        row = self.read()
        if not row or "chain" not in row:
            return None
        directory = self._payload_dir()
        decoder = quantize_mod.DeltaDecoder()
        try:
            for _version, fname in row["chain"]:
                decoder.apply(quantize_mod.load_packet(
                    os.path.join(directory, fname)))
            return decoder.params()
        except (OSError, ValueError, KeyError,
                quantize_mod.DeltaChainBroken):
            return None  # racing a prune/rename; the next poll resolves it

    def version(self) -> int:
        row = self.read()
        return int(row["version"]) if row else -1


class MailboxSubscriber:
    """Stateful mailbox reader: applies only the new delta tail when in
    sync, resyncs through the row's chain-from-base after a gap (dropped
    delta, late join) — the subscriber half of ``publish_params``."""

    def __init__(self, mailbox: WeightMailbox, tracer=None,
                 consumer: str = "mailbox"):
        self.mailbox = mailbox
        self.resyncs = 0
        # pipeline tracing (obs/pipeline_trace.py): adoption lag is measured
        # against the publish row's OWN wall ts, so it works across
        # processes that never shared tracer state; the adopt span reuses
        # the publisher's "w<host>-<version>" trace id, which is what lets
        # trace_export draw the publish -> adopt flow arrow across hosts.
        self._tracer = tracer
        self._consumer = consumer
        from rainbow_iqn_apex_tpu.utils import quantize as quantize_mod

        self._quantize = quantize_mod
        self._decoder = quantize_mod.DeltaDecoder()

    @property
    def version(self) -> int:
        return self._decoder.version

    def _note_adopt(self, row: Dict[str, Any], t0: float) -> None:
        if self._tracer is None:
            return
        version = int(row["version"])
        pub_ts = row.get("ts")
        lag_ms = (None if pub_ts is None
                  else max((time.time() - float(pub_ts)) * 1e3, 0.0))
        self._tracer.note_adopt(self._consumer, version, lag_ms=lag_ms)
        if self._tracer.sampled(version):
            self._tracer.emit_span(
                "adopt", f"w{int(row.get('pub_host', 0))}-{version}", t0,
                version=version, consumer=self._consumer,
            )

    def poll(self) -> Optional[Any]:
        """Returns the reconstructed fp32 params when a NEW version landed,
        None otherwise.  Bit-exact with the publisher's reconstruction."""
        row = self.mailbox.read()
        if not row or "chain" not in row:
            return None
        if int(row["version"]) <= self._decoder.version:
            return None
        t_adopt0 = time.time()
        directory = self.mailbox._payload_dir()
        chain = row["chain"]
        try:
            packets = [self._quantize.load_packet(os.path.join(directory, f))
                       for _v, f in chain]
            try:
                out = self._decoder.apply_chain(
                    [p for p in packets if p.version > self._decoder.version])
            except self._quantize.DeltaChainBroken:
                # missed packet(s) beyond the published chain: fresh-base
                # resync through the full chain (always converges — the
                # chain starts with its base)
                self.resyncs += 1
                self._decoder = self._quantize.DeltaDecoder()
                out = self._decoder.apply_chain(packets)
        except (OSError, ValueError, KeyError):
            return None  # racing a prune/rename; retry next poll
        try:
            # telemetry AFTER the decode try/except: the decoder has already
            # advanced, so a tracer/row hiccup here swallowing the params
            # would silently drop an adopted version forever (the next poll
            # would see version <= decoder.version and deliver nothing)
            self._note_adopt(row, t_adopt0)
        except Exception:
            pass
        return out


# ----------------------------------------------------------- staleness fence
class StalenessFence:
    """Pause acting when the adopted weight version trails the published one
    by more than ``max_lag`` publishes (IMPACT: unbounded staleness corrupts
    learning silently — shedding frames is strictly better than feeding
    replay off-policy-beyond-budget experience).

    ``observe`` returns True when acting is allowed.  Fence/resume edges are
    emitted once per episode as ``actor_fenced`` rows (``action`` is
    "fence" or "resume"); frames refused while fenced accumulate in
    ``shed_frames``.  ``max_lag <= 0`` disables fencing but keeps the
    ``weight_version_lag`` gauge live."""

    def __init__(self, max_lag: int, metrics=None, registry=None,
                 role: str = "actor", game: Optional[str] = None):
        self.max_lag = int(max_lag)
        self.metrics = metrics
        self.registry = registry
        self.role = role
        # multi-game attribution (multitask/): a fence episode on a
        # game-pinned actor lane names WHICH game sheds frames — the
        # "one game collapsed while others train" triage key
        # (docs/RUNBOOK.md)
        self.game = game
        self.fenced = False
        self.fences = 0
        self.shed_frames = 0
        self.lag = 0

    def _gauge(self, name: str, value: float) -> None:
        if self.registry is not None:
            self.registry.gauge(name, self.role).set(value)

    def _edge(self, action: str, step: int) -> None:
        if self.metrics is None:
            return
        extra = {} if self.game is None else {"game": self.game}
        self.metrics.log("actor_fenced", action=action, lag=self.lag,
                         max_lag=self.max_lag, step=int(step), **extra)

    def observe(self, held_version: int, published_version: int,
                step: int = 0, frames_at_stake: int = 0) -> bool:
        self.lag = max(int(published_version) - int(held_version), 0)
        self._gauge("weight_version_lag", self.lag)
        if self.max_lag <= 0:
            return True
        if self.lag > self.max_lag:
            if not self.fenced:
                self.fenced = True
                self.fences += 1
                self._edge("fence", step)
            self.shed_frames += int(frames_at_stake)
            self._gauge("actor_shed_frames", self.shed_frames)
            return False
        if self.fenced:
            self.fenced = False
            self._edge("resume", step)
        return True


# -------------------------------------------------------- respawn supervision
class RoleSupervisor:
    """Process-level respawn-with-backoff under the shared FailureBudget.

    Roles are registered with a ``spawn(epoch)`` callable returning a
    process-like object (``poll()`` -> rc or None, ``kill()``).  ``poll``
    drives the state machine:

        running --exit rc!=0--> backoff (delay = RetryPolicy schedule,
                          fault row ``actor_dead``) --due--> running at
                          epoch+1 (fault row ``actor_respawn``)
        running --exit rc!=0, budget exhausted--> evicted (permanent;
                          fault row ``actor_evicted`` — the fleet layer's
                          ``train_aborted``)
        running --exit rc=0--> done (terminal SUCCESS — a finite role,
                          e.g. a league member reaching t_max; fault row
                          ``actor_done``, never window-degrading)

    The backoff schedule comes from `faults.RetryPolicy.delays()` — the one
    retry policy training IO and serving hot-swap already share — so two
    soaks with the same seed respawn identically."""

    def __init__(self, backoff: faults.RetryPolicy,
                 budget: Optional[faults.FailureBudget] = None,
                 metrics=None, registry=None,
                 clock: Callable[[], float] = time.monotonic,
                 healthy_uptime_s: float = 60.0):
        self.backoff = backoff
        self.budget = budget if budget is not None else faults.FailureBudget(
            max_failures=max(backoff.attempts - 1, 1)
        )
        self.metrics = metrics
        self.registry = registry
        self.clock = clock
        # an incarnation that survives this long clears its role's strike
        # count (FailureBudget.clear): the budget bounds CONSECUTIVE crash
        # loops, not lifetime preemptions — a host preempted once a day for
        # a week is healthy infrastructure, not a candidate for eviction
        self.healthy_uptime_s = float(healthy_uptime_s)
        self._delays = list(backoff.delays()) or [backoff.base_delay_s]
        self._roles: Dict[str, Dict[str, Any]] = {}

    @classmethod
    def from_config(cls, cfg, metrics=None, registry=None,
                    clock: Callable[[], float] = time.monotonic
                    ) -> "RoleSupervisor":
        """The Config wiring for the respawn knobs: a role gets exactly
        ``respawn_attempts`` RESTARTS before eviction (the budget poisons on
        failure N+1, matching docs/RESILIENCE.md and launch_apex.sh's shell
        mirror), backed off from ``respawn_base_s`` to ``respawn_max_s``
        with the shared seeded jitter.  scripts/chaos_soak.py defaults its
        CLI to the same fields."""
        attempts = max(int(cfg.respawn_attempts), 1)
        return cls(
            faults.RetryPolicy(
                attempts=attempts + 1,
                base_delay_s=cfg.respawn_base_s,
                max_delay_s=cfg.respawn_max_s,
                seed=getattr(cfg, "seed", 0),
            ),
            budget=faults.FailureBudget(attempts + 1),
            metrics=metrics, registry=registry, clock=clock,
        )

    # ------------------------------------------------------------- registry
    def register(self, role_id: str, spawn: Callable[[int], Any],
                 epoch: int = 0, proc: Any = None,
                 meta: Optional[Dict[str, Any]] = None) -> Any:
        """Track ``role_id``; spawns immediately at ``epoch`` unless a live
        ``proc`` for that epoch is handed in.  ``meta`` fields (e.g.
        ``role_host``, the host id RunHealth keys eviction on) ride in every
        event row this role emits."""
        if proc is None:
            proc = spawn(epoch)
        self._roles[role_id] = {
            "spawn": spawn, "proc": proc, "epoch": int(epoch),
            "state": "running", "due": 0.0, "meta": dict(meta or {}),
            "since": self.clock(), "restarts": 0, "exits": 0,
        }
        self._observe()
        return proc

    def _observe(self) -> None:
        if self.registry is None:
            return
        states = [r["state"] for r in self._roles.values()]
        self.registry.gauge("roles_running", "supervisor").set(
            states.count("running"))
        self.registry.gauge("roles_evicted", "supervisor").set(
            states.count("evicted"))

    def _report(self, event: str, **fields: Any) -> None:
        if self.metrics is not None:
            self.metrics.log("fault", event=event, **fields)

    # ----------------------------------------------------------- supervision
    def poll(self, step: int = 0) -> List[Dict[str, Any]]:
        """One supervision sweep; returns the transition events it emitted."""
        events: List[Dict[str, Any]] = []
        for role_id, r in self._roles.items():
            if r["state"] == "running":
                rc = r["proc"].poll() if r["proc"] is not None else 1
                if rc is None:
                    if (self.budget.failures(role_id)
                            and self.clock() - r["since"]
                            >= self.healthy_uptime_s):
                        # the incarnation proved healthy: strikes are for
                        # consecutive crash loops, not lifetime preemptions
                        self.budget.clear(role_id)
                    continue
                if rc == 0:
                    # a clean completion (finite role — e.g. a league member
                    # reaching t_max) is terminal SUCCESS: no strike, no
                    # respawn-from-scratch, no eviction — treating it as a
                    # crash would retrain completed members forever and then
                    # report a healthy population as collapsed
                    r["state"] = "done"
                    r["exits"] += 1
                    self.budget.clear(role_id)
                    ev = {"event": "actor_done", "role": role_id, "rc": 0,
                          "epoch": r["epoch"], "step": step, **r["meta"]}
                    self._report(**ev)
                    events.append(ev)
                    continue
                n = self.budget.record(role_id)
                r["exits"] += 1
                if self.registry is not None:
                    # per-role exit/restart/evict counters (league/ needs to
                    # distinguish a CRASHING member from a LOSING one — a
                    # loser trains fine and scores low, a crasher restarts;
                    # obs_report reads the same counters off `league` rows)
                    self.registry.counter("role_exits", role_id).inc()
                if self.budget.poisoned(role_id):
                    r["state"] = "evicted"
                    if self.registry is not None:
                        self.registry.counter("role_evictions", role_id).inc()
                    ev = {"event": "actor_evicted", "role": role_id, "rc": rc,
                          "failures": n, "epoch": r["epoch"], "step": step,
                          **r["meta"]}
                else:
                    delay = self._delays[min(n - 1, len(self._delays) - 1)]
                    r["state"] = "backoff"
                    r["due"] = self.clock() + delay
                    ev = {"event": "actor_dead", "role": role_id, "rc": rc,
                          "failures": n, "epoch": r["epoch"], "step": step,
                          "respawn_in_s": round(delay, 3), **r["meta"]}
                self._report(**ev)
                events.append(ev)
            elif r["state"] == "backoff" and self.clock() >= r["due"]:
                r["epoch"] += 1
                r["proc"] = r["spawn"](r["epoch"])
                r["state"] = "running"
                r["since"] = self.clock()
                r["restarts"] += 1
                if self.registry is not None:
                    self.registry.counter("role_restarts", role_id).inc()
                ev = {"event": "actor_respawn", "role": role_id,
                      "epoch": r["epoch"],
                      "attempt": self.budget.failures(role_id), "step": step,
                      **r["meta"]}
                self._report(**ev)
                events.append(ev)
        self._observe()
        return events

    def release(self, role_id: str) -> None:
        """Deliberate decommission (autoscaler scale-in): stop tracking the
        role WITHOUT an eviction event — a shrunk fleet is a sizing decision,
        not a failure.  The caller stops the process itself; releasing first
        means the exit can never race a poll() into a spurious actor_dead."""
        self._roles.pop(role_id, None)
        self.budget.clear(role_id)
        self._observe()

    # ------------------------------------------------------------- inspection
    def stats(self, role_id: Optional[str] = None) -> Dict[str, Any]:
        """Per-role lifecycle counters: {role: {state, epoch, restarts,
        exits, failures}} (or one role's dict when ``role_id`` is given).
        The league controller uses these to tell a CRASHING member (climbing
        restarts) from a LOSING one (healthy process, low fitness) — the
        two need opposite responses (docs/LEAGUE.md triage)."""
        def one(rid: str, r: Dict[str, Any]) -> Dict[str, Any]:
            return {
                "state": r["state"], "epoch": r["epoch"],
                "restarts": r["restarts"], "exits": r["exits"],
                "failures": self.budget.failures(rid),
            }

        if role_id is not None:
            return one(role_id, self._roles[role_id])
        return {rid: one(rid, r) for rid, r in self._roles.items()}

    def state(self, role_id: str) -> str:
        return self._roles[role_id]["state"]

    def epoch(self, role_id: str) -> int:
        return self._roles[role_id]["epoch"]

    def proc(self, role_id: str) -> Any:
        return self._roles[role_id]["proc"]

    def evicted(self) -> List[str]:
        return sorted(r for r, s in self._roles.items()
                      if s["state"] == "evicted")

    def all_settled(self) -> bool:
        """No respawn pending: every role is either running or evicted."""
        return all(r["state"] != "backoff" for r in self._roles.values())

    def stop_all(self) -> None:
        for r in self._roles.values():
            proc = r["proc"]
            if proc is not None and proc.poll() is None:
                try:
                    proc.kill()
                except OSError:
                    pass
