"""Mesh-parallel R2D2: the recurrent architecture under the Ape-X topology.

Same shape as parallel/apex.py (SURVEY.md §2 rows 6-8 mapping), with the
recurrent differences:
- actor inference is lane-sharded AND stateful: the per-lane LSTM (c, h)
  lives on the actor mesh, sharded with the lanes, and is carried on-device
  tick to tick (episode cuts zero it via a device-side mask — no per-tick
  host round-trip of the state);
- the host still snapshots the pre-step state each tick (one device->host
  copy) because the sequence replay must store exact states for burn-in
  (Kapturowski et al. stored-state replay);
- the learner runs the sequence learn step dp-sharded (numerics proven equal
  to single-device in tests/test_r2d2_sharding.py);
- weight publish is the same bf16 cross-mesh broadcast.
"""

from __future__ import annotations

import collections
import os
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from rainbow_iqn_apex_tpu.agents.agent import FrameStacker
from rainbow_iqn_apex_tpu.config import Config
from rainbow_iqn_apex_tpu.envs import make_vector_env
from rainbow_iqn_apex_tpu.ops.r2d2 import (
    R2D2TrainState,
    SequenceBatch,
    as_actor_input,
    build_r2d2_act_step,
    build_r2d2_learn_step,
    init_r2d2_state,
    to_device_seq_batch,
)
from rainbow_iqn_apex_tpu.parallel.mesh import (
    actor_mesh,
    batch_sharding,
    learner_mesh,
    replicated,
    split_devices,
)
from rainbow_iqn_apex_tpu.replay.sequence import SequenceReplay, SequenceSample
from rainbow_iqn_apex_tpu.train import priority_beta
from rainbow_iqn_apex_tpu.utils.checkpoint import (
    Checkpointer,
    maybe_restore_replay,
    save_replay_snapshot,
)
from rainbow_iqn_apex_tpu.utils.logging import MetricsLogger
from rainbow_iqn_apex_tpu.utils.prefetch import BatchPrefetcher


class R2D2ApexDriver:
    def __init__(
        self,
        cfg: Config,
        num_actions: int,
        frame_shape: Tuple[int, int],
        lanes: int,
        devices: Optional[Sequence[jax.Device]] = None,
    ):
        self.cfg = cfg
        ldevs, adevs = split_devices(devices, cfg.learner_devices)
        self.lmesh = learner_mesh(ldevs)
        self.amesh = actor_mesh(adevs)
        self.n_actor_devices = len(adevs)
        if lanes % self.n_actor_devices:
            raise ValueError(
                f"lanes {lanes} must divide across {self.n_actor_devices} actor devices"
            )
        rep_l, rep_a = replicated(self.lmesh), replicated(self.amesh)
        lane_sh = batch_sharding(self.amesh, "actor")

        self.key = jax.random.PRNGKey(cfg.seed)
        self.key, k_init = jax.random.split(self.key)
        self.state: R2D2TrainState = jax.device_put(
            init_r2d2_state(cfg, num_actions, k_init, frame_shape), rep_l
        )

        self._learn = jax.jit(
            build_r2d2_learn_step(cfg, num_actions),
            in_shardings=(rep_l, batch_sharding(self.lmesh, "dp"), rep_l),
            donate_argnums=0,
        )
        # act: obs + (c, h) lane-sharded; params replicated on the actor mesh
        self._act = jax.jit(
            build_r2d2_act_step(cfg, num_actions, use_noise=True),
            in_shardings=(rep_a, lane_sh, (lane_sh, lane_sh), rep_a),
            out_shardings=(lane_sh, lane_sh, (lane_sh, lane_sh)),
        )
        # device-side episode-cut mask for the carried state
        self._mask_state = jax.jit(
            lambda st, keep: jax.tree.map(lambda x: x * keep[:, None], st),
            in_shardings=((lane_sh, lane_sh), lane_sh),
            out_shardings=(lane_sh, lane_sh),
        )
        if cfg.bf16_weight_sync:
            self._cast = jax.jit(
                lambda p: jax.tree.map(lambda x: x.astype(jnp.bfloat16), p)
            )
            self._uncast = jax.jit(
                lambda p: jax.tree.map(lambda x: x.astype(jnp.float32), p),
                out_shardings=rep_a,
            )
        self._rep_a = rep_a
        self._lane_sh = lane_sh
        self.actor_params = None
        self.lstm_state = jax.device_put(
            (
                jnp.zeros((lanes, cfg.lstm_size), jnp.float32),
                jnp.zeros((lanes, cfg.lstm_size), jnp.float32),
            ),
            lane_sh,  # applied to both (c, h) leaves
        )
        self.publish_weights()

    def _next_key(self):
        self.key, k = jax.random.split(self.key)
        return k

    def publish_weights(self) -> None:
        p = self.state.params
        if self.cfg.bf16_weight_sync:
            p = self._uncast(jax.device_put(self._cast(p), self._rep_a))
        else:
            p = jax.device_put(p, self._rep_a)
        self.actor_params = p

    def restore(self, ckpt) -> Dict[str, Any]:
        """Load the latest checkpoint into the learner mesh and re-publish
        actor weights; returns the checkpoint's extra metadata."""
        state, extra = ckpt.restore(self.state)
        self.state = jax.device_put(state, replicated(self.lmesh))
        self.publish_weights()
        return extra

    def act(self, obs: np.ndarray) -> Tuple[np.ndarray, Tuple[np.ndarray, np.ndarray]]:
        """obs [L, H, W] u8 (history 1) or [L, H, W, hist] stacked ->
        (actions [L], pre-step host state (c, h)).

        The pre-step state snapshot is what the sequence replay stores."""
        pre_c = np.asarray(self.lstm_state[0])
        pre_h = np.asarray(self.lstm_state[1])
        x = as_actor_input(obs, self.cfg.history_length)
        a, _q, self.lstm_state = self._act(
            self.actor_params, x, self.lstm_state, self._next_key()
        )
        return np.asarray(a), (pre_c, pre_h)

    def reset_lanes(self, cuts: np.ndarray) -> None:
        keep = jnp.asarray(1.0 - cuts.astype(np.float32))
        self.lstm_state = self._mask_state(self.lstm_state, keep)

    def learn_batch(self, batch: SequenceBatch) -> Dict[str, Any]:
        self.state, info = self._learn(self.state, batch, self._next_key())
        return info

    @property
    def step(self) -> int:
        return int(self.state.step)


def _eval_r2d2_learner(cfg: Config, env, driver: "R2D2ApexDriver") -> Dict[str, Any]:
    """Evaluate the learner's current params on a single-device eval agent."""
    from rainbow_iqn_apex_tpu.train_r2d2 import R2D2Agent, evaluate_r2d2

    eval_agent = R2D2Agent(
        cfg, env.num_actions, env.frame_shape, jax.random.PRNGKey(cfg.seed + 1),
        train=False,
    )
    eval_agent.state = jax.device_put(driver.state, jax.devices()[0])
    return evaluate_r2d2(cfg, eval_agent, seed=cfg.seed + 977)


def train_apex_r2d2(cfg: Config, max_frames: Optional[int] = None) -> Dict[str, Any]:
    total_frames = max_frames or cfg.t_max
    lanes = cfg.num_actors * cfg.num_envs_per_actor
    env = make_vector_env(cfg.env_id, lanes, seed=cfg.seed)
    driver = R2D2ApexDriver(cfg, env.num_actions, env.frame_shape, lanes)

    seq_total = cfg.r2d2_burn_in + cfg.r2d2_seq_len
    memory = SequenceReplay(
        capacity=max(cfg.memory_capacity // seq_total, 64),
        seq_len=seq_total,
        frame_shape=env.frame_shape,
        lstm_size=cfg.lstm_size,
        lanes=lanes,
        stride=max(seq_total - cfg.r2d2_overlap, 1),
        priority_exponent=cfg.priority_exponent,
        priority_eps=cfg.priority_eps,
        seed=cfg.seed,
    )
    run_dir = os.path.join(cfg.results_dir, cfg.run_id)
    metrics = MetricsLogger(os.path.join(run_dir, "metrics.jsonl"), cfg.run_id)
    ckpt = Checkpointer(os.path.join(cfg.checkpoint_dir, cfg.run_id))

    frames = 0
    last_pub = 0
    if cfg.resume and ckpt.latest_step() is not None:
        extra = driver.restore(ckpt)
        frames = int(extra.get("frames", 0))
        last_pub = driver.step
        maybe_restore_replay(cfg, memory)
        metrics.log("resume", step=driver.step, frames=frames)

    obs = env.reset()
    stacker = FrameStacker(lanes, env.frame_shape, cfg.history_length)
    returns: collections.deque = collections.deque(maxlen=100)
    prefetcher: Optional[BatchPrefetcher] = None
    learn_start_seqs = max(cfg.learn_start // seq_total, 8)
    frames_per_step = cfg.replay_ratio * cfg.r2d2_seq_len

    try:
        while frames < total_frames:
            actions, (pre_c, pre_h) = driver.act(stacker.push(obs))
            new_obs, rewards, terminals, truncs, ep_returns = env.step(actions)
            cuts = terminals | truncs
            memory.append_batch(
                obs, actions, rewards, terminals, pre_c, pre_h, truncations=truncs
            )
            driver.reset_lanes(cuts)
            stacker.reset_lanes(cuts)
            obs = new_obs
            frames += lanes
            for r in ep_returns[~np.isnan(ep_returns)]:
                returns.append(float(r))

            if len(memory) >= learn_start_seqs:
                if cfg.prefetch_depth > 0 and prefetcher is None:
                    prefetcher = BatchPrefetcher(
                        lambda: (
                            (s := memory.sample(
                                cfg.batch_size, priority_beta(cfg, frames)
                            )).idx,
                            to_device_seq_batch(s),
                        ),
                        depth=cfg.prefetch_depth,
                        device_put=False,
                    )
                steps_due = frames // frames_per_step - driver.step
                for _ in range(max(steps_due, 0)):
                    if prefetcher is not None:
                        idx, batch = prefetcher.get()
                    else:
                        s = memory.sample(cfg.batch_size, priority_beta(cfg, frames))
                        idx, batch = s.idx, to_device_seq_batch(s)
                    info = driver.learn_batch(batch)
                    memory.update_priorities(idx, np.asarray(info["priorities"]))
                    step = driver.step
                    if step - last_pub >= cfg.weight_publish_interval:
                        driver.publish_weights()
                        last_pub = step
                    if step % cfg.metrics_interval == 0:
                        metrics.log(
                            "train",
                            step=step,
                            frames=frames,
                            fps=metrics.fps(frames),
                            loss=float(info["loss"]),
                            q_mean=float(info["q_mean"]),
                            mean_return=float(np.mean(returns)) if returns else float("nan"),
                            sequences=len(memory),
                            staleness=step - last_pub,
                        )
                    if cfg.eval_interval and step % cfg.eval_interval == 0:
                        metrics.log(
                            "eval", step=step, **_eval_r2d2_learner(cfg, env, driver)
                        )
                    if cfg.checkpoint_interval and step % cfg.checkpoint_interval == 0:
                        ckpt.save(step, driver.state, {"frames": frames})
                        save_replay_snapshot(cfg, memory)
    finally:
        if prefetcher is not None:
            prefetcher.close()

    final_eval = _eval_r2d2_learner(cfg, env, driver)
    metrics.log("eval", step=driver.step, **final_eval)
    ckpt.save(driver.step, driver.state, {"frames": frames})
    save_replay_snapshot(cfg, memory)
    ckpt.wait()
    metrics.close()
    return {
        "frames": frames,
        "learn_steps": driver.step,
        "lanes": lanes,
        "sequences": len(memory),
        "train_return_mean": float(np.mean(returns)) if returns else float("nan"),
        **{f"eval_{k}": v for k, v in final_eval.items()},
    }
