"""Mesh-parallel R2D2: the recurrent architecture under the Ape-X topology.

Same shape as parallel/apex.py (SURVEY.md §2 rows 6-8 mapping), with the
recurrent differences:
- actor inference is lane-sharded AND stateful: the per-lane LSTM (c, h)
  lives on the actor mesh, sharded with the lanes, and is carried on-device
  tick to tick (episode cuts zero it via a device-side mask — no per-tick
  host round-trip of the state);
- the host still snapshots the pre-step state each tick (one device->host
  copy) because the sequence replay must store exact states for burn-in
  (Kapturowski et al. stored-state replay);
- the learner runs the sequence learn step dp-sharded (numerics proven equal
  to single-device in tests/test_r2d2_sharding.py);
- weight publish is the same bf16 cross-mesh broadcast.
"""

from __future__ import annotations

import collections
import os
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from rainbow_iqn_apex_tpu.agents.agent import FrameStacker
from rainbow_iqn_apex_tpu.config import Config
from rainbow_iqn_apex_tpu.envs import make_vector_env
from rainbow_iqn_apex_tpu.obs import RunObs
from rainbow_iqn_apex_tpu.ops.r2d2 import (
    R2D2TrainState,
    SequenceBatch,
    as_actor_input,
    build_r2d2_act_step,
    build_r2d2_learn_step,
    init_r2d2_state,
    to_device_seq_batch,
)
from rainbow_iqn_apex_tpu.parallel.mesh import (
    actor_mesh,
    batch_sharding,
    learner_mesh,
    replicated,
    split_devices,
)
from rainbow_iqn_apex_tpu.parallel.multihost import (
    global_is_nq,
    host_state,
    lane_put,
    local_rows as _local_rows,
    make_global_is_weights,
    plan_hosts,
    shift_stack,
)
from rainbow_iqn_apex_tpu.parallel.supervisor import TrainSupervisor
from rainbow_iqn_apex_tpu.replay.sequence import SequenceReplay, SequenceSample
from rainbow_iqn_apex_tpu.train import priority_beta
from rainbow_iqn_apex_tpu.utils import faults, hostsync
from rainbow_iqn_apex_tpu.utils.checkpoint import (
    Checkpointer,
    maybe_restore_replay,
    maybe_resume,
    rng_extra,
    rng_from_extra,
)
from rainbow_iqn_apex_tpu.parallel.quant_publish import QuantPublishMixin
from rainbow_iqn_apex_tpu.utils.quantize import wrap_act_quantized
from rainbow_iqn_apex_tpu.utils.logging import MetricsLogger
from rainbow_iqn_apex_tpu.utils.prefetch import BatchPrefetcher
from rainbow_iqn_apex_tpu.utils.writeback import (
    RingCommitter,
    WritebackRing,
    pipeline_gauges,
)


class R2D2ApexDriver(QuantPublishMixin):
    """Recurrent apex driver; the gated quantized publish surface is the
    shared `QuantPublishMixin` (the two drivers must not drift on it)."""

    def __init__(
        self,
        cfg: Config,
        num_actions: int,
        frame_shape: Tuple[int, int],
        lanes: int,
        devices: Optional[Sequence[jax.Device]] = None,
    ):
        self.cfg = cfg
        ldevs, adevs = split_devices(devices, cfg.learner_devices)
        self.lmesh = learner_mesh(ldevs)
        self.amesh = actor_mesh(adevs)
        self.n_actor_devices = len(adevs)
        if lanes % self.n_actor_devices:
            raise ValueError(
                f"lanes {lanes} must divide across {self.n_actor_devices} actor devices"
            )
        rep_l, rep_a = replicated(self.lmesh), replicated(self.amesh)
        lane_sh = batch_sharding(self.amesh, "actor")
        self._multihost = jax.process_count() > 1
        if self._multihost and cfg.learner_devices:
            raise ValueError(
                "multi-host R2D2 apex needs learner_devices=0 (every chip "
                "plays both roles) so the weight publish stays host-local"
            )

        self.key = jax.random.PRNGKey(cfg.seed)
        self.key, k_init = jax.random.split(self.key)
        self._host_step: Optional[int] = None  # host mirror of state.step
        self.state: R2D2TrainState = jax.device_put(
            init_r2d2_state(cfg, num_actions, k_init, frame_shape), rep_l
        )

        self._batch_sh = batch_sharding(self.lmesh, "dp")
        self._learn = jax.jit(
            build_r2d2_learn_step(cfg, num_actions),
            in_shardings=(rep_l, self._batch_sh, rep_l),
            donate_argnums=0,
        )
        # multi-host: global IS-weight renormalization (shared helper —
        # sequence counts are not lockstep across hosts, so each row's N is
        # its own host's estimate, folded into nq per row)
        self._global_is_weights = make_global_is_weights(self._batch_sh)
        # act: obs + (c, h) lane-sharded; params replicated on the actor mesh
        act_fn = build_r2d2_act_step(cfg, num_actions, use_noise=True)
        self._act = jax.jit(
            act_fn,
            in_shardings=(rep_a, lane_sh, (lane_sh, lane_sh), rep_a),
            out_shardings=(lane_sh, lane_sh, (lane_sh, lane_sh)),
        )
        # device-resident frame stacking (shared shift with ApexDriver): the
        # host ships ONE [L, H, W] frame per tick; cut lanes are zeroed
        # in-graph before the shift.  Only used when history_length > 1.
        def stack_act(params, stack, frame, keep, lstm_state, key):
            stack = shift_stack(stack, frame, keep)
            a, q, new_state = act_fn(params, stack, lstm_state, key)
            return a, q, new_state, stack

        self._stack_act = jax.jit(
            stack_act,
            in_shardings=(
                rep_a, lane_sh, lane_sh, lane_sh, (lane_sh, lane_sh), rep_a,
            ),
            out_shardings=(lane_sh, lane_sh, (lane_sh, lane_sh), lane_sh),
            donate_argnums=1,
        )
        self.actor_stack = None  # created lazily at the first act_frames
        # device-side episode-cut mask for the carried state
        self._mask_state = jax.jit(
            lambda st, keep: jax.tree.map(lambda x: x * keep[:, None], st),
            in_shardings=((lane_sh, lane_sh), lane_sh),
            out_shardings=(lane_sh, lane_sh),
        )
        if cfg.bf16_weight_sync:
            self._cast = jax.jit(
                lambda p: jax.tree.map(lambda x: x.astype(jnp.bfloat16), p)
            )
            self._uncast = jax.jit(
                lambda p: jax.tree.map(lambda x: x.astype(jnp.float32), p),
                out_shardings=rep_a,
            )
        self._rep_a = rep_a
        self._lane_sh = lane_sh
        self._put_lanes = lane_put(lane_sh)
        self.actor_params = None
        # quantized actor lanes — the shared QuantPublishMixin surface,
        # gated on a replay-drawn calibration batch under a zero LSTM state
        # (the episode-start condition every lane revisits)
        if self._init_quant_publish(cfg, multihost=self._multihost) != "off":
            act_q_fn = wrap_act_quantized(act_fn)
            self._act_q = jax.jit(
                act_q_fn,
                in_shardings=(rep_a, lane_sh, (lane_sh, lane_sh), rep_a),
                out_shardings=(lane_sh, lane_sh, (lane_sh, lane_sh)),
            )

            def stack_act_q(qparams, stack, frame, keep, lstm_state, key):
                stack = shift_stack(stack, frame, keep)
                a, q, new_state = act_q_fn(qparams, stack, lstm_state, key)
                return a, q, new_state, stack

            self._stack_act_q = jax.jit(
                stack_act_q,
                in_shardings=(
                    rep_a, lane_sh, lane_sh, lane_sh, (lane_sh, lane_sh),
                    rep_a,
                ),
                out_shardings=(
                    lane_sh, lane_sh, (lane_sh, lane_sh), lane_sh,
                ),
                donate_argnums=1,
            )
            # the gate runs on the LEARNER mesh copy (plain jit)
            self._gate_act32 = jax.jit(act_fn)
            self._gate_actq = jax.jit(act_q_fn)
        # lanes is the GLOBAL lane count; each host materialises only its
        # local rows (make_array == device_put when single-process)
        local_zeros = np.zeros(
            (lanes // jax.process_count(), cfg.lstm_size), np.float32
        )
        self.lstm_state = (
            self._put_lanes(local_zeros),
            self._put_lanes(local_zeros),
        )
        self.weights_version = 0
        self.actor_weights_version = 0
        self.publish_weights()

    def _next_key(self):
        self.key, k = jax.random.split(self.key)
        return k

    # publish_weights / attach_obs / wants_calibration and the gated
    # quantized broadcast live in QuantPublishMixin (shared with
    # ApexDriver); only the act-signature-shaped hooks are defined here.
    def set_calibration(self, obs_batch: np.ndarray) -> None:
        """Calibration frames ([n, H, W, C], replay-drawn) for the gate;
        compared under a zero LSTM state — the episode-start condition."""
        n = min(len(obs_batch), max(int(self.cfg.quant_calib_batch), 1))
        obs = np.asarray(obs_batch[:n], np.uint8)
        self._calib_obs = jnp.asarray(obs)
        zeros = jnp.zeros((n, self.cfg.lstm_size), jnp.float32)
        self._calib_state = (zeros, zeros)

    def _gate_actions(self, params, qparams):
        a32, _, _ = self._gate_act32(
            params, self._calib_obs, self._calib_state, self._gate_key)
        aq, _, _ = self._gate_actq(
            qparams, self._calib_obs, self._calib_state, self._gate_key)
        return a32, aq

    def load_state(self, state, extra: Optional[Dict[str, Any]] = None) -> None:
        """Place a restored R2D2TrainState onto the learner mesh, pick up
        the saved RNG stream when present, re-publish actor weights.  The
        weight-version counter resumes from the checkpoint (same fence
        contract as ApexDriver.load_state)."""
        self.state = jax.device_put(state, replicated(self.lmesh))
        self.key = jnp.asarray(rng_from_extra(extra or {}, self.key))
        saved = int((extra or {}).get("weights_version", 0))
        self.weights_version = max(self.weights_version, saved)
        self.publish_weights()

    def restore(self, ckpt) -> Dict[str, Any]:
        """Load the latest checkpoint into the learner mesh and re-publish
        actor weights; returns the checkpoint's extra metadata."""
        state, extra = ckpt.restore(self.state)
        self.load_state(state, extra)
        return extra

    def load_snapshot(self, state, key) -> None:
        """NaN-guard rollback (parallel/supervisor.py); actor params stay as
        last published — the poisoned state never reached them."""
        self.state = jax.device_put(state, replicated(self.lmesh))
        self.key = jnp.asarray(key)

    def act(self, obs: np.ndarray) -> Tuple[np.ndarray, Tuple[np.ndarray, np.ndarray]]:
        """obs [L_local, H, W] u8 (history 1) or [L_local, H, W, hist]
        stacked -> (actions [L_local], pre-step host state (c, h)).

        The pre-step state snapshot is what the sequence replay stores.
        Multi-host: this host feeds/reads only its local lane rows; the
        carried LSTM state stays device-resident and lane-sharded over the
        global actor mesh."""
        # the actor->env hand-off (actions) and the stored-state snapshot
        # the sequence replay requires are OBLIGATORY host materializations
        # on the actor half — sanctioned syncs, not learner-hot-path
        # regressions (docs/PERFORMANCE.md inventory)
        act = self._act_q if self._actor_quant else self._act
        if self._multihost:
            with hostsync.sanctioned():
                pre_c = _local_rows(self.lstm_state[0])
                pre_h = _local_rows(self.lstm_state[1])
            x = self._put_lanes(as_actor_input(obs, self.cfg.history_length))
            a, _q, self.lstm_state = act(
                self.actor_params, x, self.lstm_state, self._next_key()
            )
            with hostsync.sanctioned():
                return _local_rows(a), (pre_c, pre_h)
        with hostsync.sanctioned():
            pre_c = np.asarray(self.lstm_state[0])
            pre_h = np.asarray(self.lstm_state[1])
        x = as_actor_input(obs, self.cfg.history_length)
        a, _q, self.lstm_state = act(
            self.actor_params, x, self.lstm_state, self._next_key()
        )
        with hostsync.sanctioned():
            return np.asarray(a), (pre_c, pre_h)

    def reset_lanes(self, cuts: np.ndarray) -> None:
        keep = self._put_lanes(1.0 - cuts.astype(np.float32))
        self.lstm_state = self._mask_state(self.lstm_state, keep)

    def act_frames(
        self, frames: np.ndarray, prev_cuts: np.ndarray
    ) -> Tuple[np.ndarray, Tuple[np.ndarray, np.ndarray]]:
        """Device-stacked recurrent acting (history_length > 1): push this
        host's newest [L_local, H, W] frames into the device-resident stack
        (zeroing lanes cut LAST tick) and act; returns (actions, pre-step
        LSTM state snapshot) exactly like act().  The LSTM state itself is
        reset separately via reset_lanes (the loop's existing contract)."""
        with hostsync.sanctioned():  # stored-state snapshot (actor half)
            if self._multihost:
                pre_c = _local_rows(self.lstm_state[0])
                pre_h = _local_rows(self.lstm_state[1])
            else:
                pre_c = np.asarray(self.lstm_state[0])
                pre_h = np.asarray(self.lstm_state[1])
        if self.actor_stack is None:
            h, w = frames.shape[1], frames.shape[2]
            self.actor_stack = self._put_lanes(
                np.zeros((frames.shape[0], h, w, self.cfg.history_length), np.uint8)
            )
        keep = self._put_lanes((~np.asarray(prev_cuts, bool)).astype(np.uint8))
        stack_act = self._stack_act_q if self._actor_quant else self._stack_act
        a, _q, self.lstm_state, self.actor_stack = stack_act(
            self.actor_params,
            self.actor_stack,
            self._put_lanes(np.asarray(frames, np.uint8)),
            keep,
            self.lstm_state,
            self._next_key(),
        )
        with hostsync.sanctioned():  # obligatory actor->env hand-off
            if self._multihost:
                return _local_rows(a), (pre_c, pre_h)
            return np.asarray(a), (pre_c, pre_h)

    def learn_batch(self, batch: SequenceBatch) -> Dict[str, Any]:
        """Dispatch one sequence learn step; ``info`` stays DEVICE arrays
        (async dispatch) — the write-back ring decides when to sync."""
        self._state, info = self._learn(self._state, batch, self._next_key())
        if self._host_step is not None:
            self._host_step += 1
        return info

    def learn_local(
        self, sample, global_size: int, beta: float
    ) -> Dict[str, Any]:
        """Sequence learn step fed from this host's local sub-batch; IS
        weights re-derived over the assembled GLOBAL batch exactly as in
        ApexDriver.learn_local (fixed per-host quota => uniform host
        mixture: q(i) = prob_local(i) / n_hosts).  ``priorities`` stay the
        GLOBAL device array — the ring's ``priorities_to_host`` hook
        (multihost.local_rows) extracts this host's rows at retirement."""
        put = lambda x, dt: jax.make_array_from_process_local_data(  # noqa: E731
            self._batch_sh, np.ascontiguousarray(x, dt)
        )
        nq = put(global_is_nq(sample.prob, global_size), np.float32)
        weight = self._global_is_weights(nq, jnp.float32(beta))
        batch = SequenceBatch(
            obs=put(sample.obs, np.uint8),
            action=put(sample.action, np.int32),
            reward=put(sample.reward, np.float32),
            done=put(sample.done, bool),
            valid=put(sample.valid, bool),
            init_c=put(sample.init_c, np.float32),
            init_h=put(sample.init_h, np.float32),
            weight=weight,
        )
        return self.learn_batch(batch)

    # `state` invalidates the host step mirror on direct assignment;
    # learn_batch bypasses the setter and increments it (same contract as
    # ApexDriver) so per-step `driver.step` reads never touch the device.
    @property
    def state(self) -> R2D2TrainState:
        return self._state

    @state.setter
    def state(self, value: R2D2TrainState) -> None:
        self._state = value
        self._host_step = None

    @property
    def step(self) -> int:
        if self._host_step is None:
            with hostsync.sanctioned():
                self._host_step = int(np.asarray(self._state.step))
        return self._host_step


def _eval_r2d2_learner(cfg: Config, env, driver: "R2D2ApexDriver") -> Dict[str, Any]:
    """Evaluate the learner's current params on a single-device eval agent."""
    from rainbow_iqn_apex_tpu.train_r2d2 import R2D2Agent, evaluate_r2d2

    eval_agent = R2D2Agent(
        cfg, env.num_actions, env.frame_shape, jax.random.PRNGKey(cfg.seed + 1),
        train=False,
    )
    eval_agent.state = jax.device_put(host_state(driver.state), jax.local_devices()[0])
    return evaluate_r2d2(cfg, eval_agent, seed=cfg.seed + 977)


def _eval_r2d2_multigame(cfg: Config, spec, env, driver: "R2D2ApexDriver",
                         metrics, step: int, games_obs) -> Dict[str, Any]:
    """Per-game r2d2 eval (docs/MULTITASK.md): the generalist net evaluated
    on each game's own padded env — one `eval` row per game (keyed by
    ``game``) plus the `eval_mt` human-normalized aggregate, the same
    emission contract as the iqn apex driver."""
    from rainbow_iqn_apex_tpu.envs import make_env
    from rainbow_iqn_apex_tpu.eval import human_normalized
    from rainbow_iqn_apex_tpu.multitask.eval import aggregate_human_normalized
    from rainbow_iqn_apex_tpu.multitask.lanes import GameLaneEnv
    from rainbow_iqn_apex_tpu.train_r2d2 import R2D2Agent, evaluate_r2d2

    eval_agent = R2D2Agent(
        cfg, env.num_actions, env.frame_shape,
        jax.random.PRNGKey(cfg.seed + 1), train=False,
    )
    eval_agent.state = jax.device_put(
        host_state(driver.state), jax.local_devices()[0])
    per_game: Dict[str, Dict[str, Any]] = {}
    per_game_hn: Dict[str, Any] = {}
    for g, name in enumerate(spec.games):
        game_env = GameLaneEnv(
            make_env(name, seed=cfg.seed + 977 + g), spec, g)
        try:
            row = evaluate_r2d2(
                cfg, eval_agent, seed=cfg.seed + 977 + g, env=game_env)
        finally:
            game_env.close()  # per-eval envs must not leak (ALE handles)
        hn = human_normalized(name, row["score_mean"])
        per_game_hn[name] = hn
        if hn is not None:
            row["human_normalized"] = hn
        per_game[name] = row
        if metrics is not None:
            metrics.log("eval", step=step, game=name, **row)
    agg = aggregate_human_normalized(per_game_hn)
    score_mean = float(np.mean([r["score_mean"] for r in per_game.values()]))
    if metrics is not None:
        metrics.log("eval_mt", step=step, score_mean=score_mean,
                    games=len(per_game), **agg)
    games_obs.note_eval({"games": per_game})
    return {"score_mean": score_mean, **agg}


def train_apex_r2d2(cfg: Config, max_frames: Optional[int] = None) -> Dict[str, Any]:
    """Mesh-parallel R2D2 Ape-X; multi-host exactly like apex.train_apex
    (same SPMD shape: local lanes/replay/sub-batches, global collectives).

    One recurrent-specific wrinkle: sequence EMISSION times depend on
    episode ends, so ``len(memory)`` is NOT lockstep-deterministic across
    hosts — the multi-host learn trigger therefore uses only the global
    frame counter (after enough ticks every lane has emitted at least one
    full window deterministically)."""
    if cfg.replay_ratio > 1:
        raise ValueError(
            "replay_ratio > 1 (clipped replay reuse) is implemented for the "
            "IQN apex/single loops; sequence-batch reuse under stored LSTM "
            "state is the recorded ROADMAP follow-up")
    total_frames = max_frames or cfg.t_max
    lanes_total = cfg.num_actors * cfg.num_envs_per_actor
    seq_total = cfg.r2d2_burn_in + cfg.r2d2_seq_len
    plan = plan_hosts(cfg, lanes_total)
    multihost, nproc = plan.multihost, plan.nproc
    lanes, lane_lo = plan.lanes, plan.lane_lo
    is_main, local_batch = plan.is_main, plan.local_batch

    # multi-game r2d2 (multitask/; docs/MULTITASK.md): per-game lane blocks
    # + per-game eval/obs rows around ONE generalist recurrent net (padded
    # suite-common frames/actions; GameLaneEnv maps out-of-range actions).
    # Task conditioning and per-game replay shards are the iqn apex
    # driver's — the sequence replay stays one prioritized tree, with
    # per-game learn-share attribution via the slot lane stamps.
    from rainbow_iqn_apex_tpu.multitask.spec import MultiGameSpec

    spec = MultiGameSpec.from_config(cfg)
    if spec is not None and multihost:
        raise ValueError(
            "multi-game apex (cfg.games) is single-host for now — per-host "
            "game partitioning of an SPMD pod is the ROADMAP follow-up")
    games_obs = games_of_lane = None
    mt_learn_rows = None
    if spec is not None:
        from rainbow_iqn_apex_tpu.multitask.lanes import (
            build_game_lanes,
            lane_games,
        )
        from rainbow_iqn_apex_tpu.multitask.obs import GamesObs

        if lanes % spec.num_games:
            raise ValueError(
                f"total lanes {lanes} must divide across "
                f"{spec.num_games} games")
        env = build_game_lanes(
            spec, lanes // spec.num_games, seed=cfg.seed + lane_lo)
        games_obs = GamesObs(spec)
        games_of_lane = lane_games(spec, lanes // spec.num_games)
        mt_learn_rows = np.zeros(spec.num_games, np.int64)
    else:
        env = make_vector_env(cfg.env_id, lanes, seed=cfg.seed + lane_lo)
    driver = R2D2ApexDriver(cfg, env.num_actions, env.frame_shape, lanes_total)

    memory = SequenceReplay(
        capacity=max(cfg.memory_capacity // (seq_total * nproc), 64),
        seq_len=seq_total,
        frame_shape=env.frame_shape,
        lstm_size=cfg.lstm_size,
        lanes=lanes,
        stride=max(seq_total - cfg.r2d2_overlap, 1),
        priority_exponent=cfg.priority_exponent,
        priority_eps=cfg.priority_eps,
        seed=cfg.seed + lane_lo,
    )
    run_dir = os.path.join(cfg.results_dir, cfg.run_id)
    metrics = MetricsLogger(
        os.path.join(run_dir, "metrics.jsonl") if is_main else None,
        cfg.run_id,
        echo=is_main,
        host=cfg.process_id,
    )
    ckpt = Checkpointer(os.path.join(cfg.checkpoint_dir, cfg.run_id))
    faults.install_from(cfg)
    obs_run = RunObs(cfg, metrics, role="learner")
    sup = TrainSupervisor(cfg, metrics=metrics, registry=obs_run.registry)
    # pipeline tracing — identical contract to train_apex (the two drivers
    # must not drift on the obs surface): always-on lag attribution, 1-in-N
    # span sampling; the r2d2 trace unit for appends is the EMITTED sequence
    from rainbow_iqn_apex_tpu.obs.pipeline_trace import PipelineTracer

    ptrace = PipelineTracer(
        metrics, obs_run.registry, cfg.trace_sample_every,
        host=cfg.process_id,
    )
    ptrace.max_weight_lag = cfg.max_weight_lag
    memory.attach_tracer(ptrace)
    driver.attach_obs(metrics, obs_run.registry, tracer=ptrace)
    if driver.quant_disabled_reason is not None:
        metrics.log("notice", event="quant_fallback_multihost",
                    reason="multihost: fp32/bf16 publish path retained")
    # lease + staleness-fence wiring, identical to train_apex (the two
    # drivers must not drift on the elastic surface — docs/RESILIENCE.md)
    from rainbow_iqn_apex_tpu.parallel.elastic import (
        HeartbeatMonitor,
        HeartbeatWriter,
        StalenessFence,
        heartbeat_dir,
        next_lease_epoch,
    )

    heartbeat = monitor = None
    if cfg.heartbeat_interval_s > 0:
        heartbeat = HeartbeatWriter(
            heartbeat_dir(cfg), cfg.process_id, cfg.heartbeat_interval_s,
            role="apex_r2d2", shard=cfg.process_id,
            epoch=next_lease_epoch(heartbeat_dir(cfg), cfg.process_id),
        )
        if spec is not None:
            # lease payloads carry the game set (same contract as apex.py)
            heartbeat.update_payload(game=",".join(spec.games))
        heartbeat.set_weight_version(driver.weights_version)
        heartbeat.start()
        if is_main:
            monitor = HeartbeatMonitor(
                heartbeat_dir(cfg), cfg.heartbeat_timeout_s,
                self_id=cfg.process_id,
            )
    fence = StalenessFence(
        cfg.max_weight_lag, metrics=metrics, registry=obs_run.registry
    )

    # device-resident sample frontier over the sequence tree (same contract
    # as train_apex — the two drivers must not drift on the sampling
    # surface): draws + IS weights in HBM, host gather via the pusher,
    # write-back retiring into the mirror, cold-path reconcile at drains
    frontier = None
    if cfg.device_sampling and cfg.sample_ahead_depth > 0:
        if multihost:
            metrics.log("notice", event="device_sampling_fallback",
                        reason="multihost: host sampling path retained")
        else:
            from rainbow_iqn_apex_tpu.replay.frontier import (
                DeviceSampleFrontier,
            )

            frontier = DeviceSampleFrontier.from_sequence(
                memory, registry=obs_run.registry, seed=cfg.seed + 31
            )

    frames = 0
    last_pub = 0
    restored = maybe_resume(cfg, ckpt, driver.state)
    if restored is not None:
        state, extra, _ = restored
        driver.load_state(state, extra)
        frames = int(extra.get("frames", 0))
        last_pub = driver.step
        maybe_restore_replay(cfg, memory)
        metrics.log("resume", step=driver.step, frames=frames)

    obs = env.reset()
    # device-resident stacking replaces the host FrameStacker whenever the
    # recurrent net takes stacked input (history_length == 1 feeds raw
    # frames and needs neither)
    use_dstack = cfg.device_frame_stack and cfg.history_length > 1
    stacker = None if use_dstack else FrameStacker(
        lanes, env.frame_shape, cfg.history_length
    )
    prev_cuts = np.zeros(lanes, bool)
    returns: collections.deque = collections.deque(maxlen=100)
    prefetcher: Optional[BatchPrefetcher] = None
    # pipelined priority write-back + deferred in-graph NaN guard — the same
    # zero-sync hot path as train_apex (utils/writeback.py; the two drivers
    # must not drift on the learner-throughput surface, which is why the
    # commit/quarantine/drain protocol is the shared RingCommitter)
    ring = WritebackRing(
        cfg.writeback_depth,
        registry=obs_run.registry,
        priorities_to_host=_local_rows if multihost else None,
        materialize_priorities=frontier is None,
        tracer=ptrace,
    )
    committer = RingCommitter(
        ring,
        frontier.update if frontier is not None else memory.update_priorities,
        sup,
        driver.load_snapshot,
        on_drain=frontier.reconcile if frontier is not None else None,
    )
    last_scalars = committer.scalars
    _commit, _drain = committer.commit, committer.drain

    learn_start_seqs = max(cfg.learn_start // seq_total, 8)  # single-host gate
    frames_per_step = cfg.frames_per_learn * cfg.r2d2_seq_len
    # multi-host learn trigger: frames-only (lockstep-deterministic), and
    # counted from THIS (re)start so a resume with a cold/torn replay
    # snapshot re-warms instead of sampling an empty buffer; by this many
    # fresh global frames every lane has emitted >= 1 full window
    frames_warm = max(cfg.learn_start, (seq_total + 1) * lanes_total)
    frames_at_start = frames

    try:
        while frames < total_frames:
            # causal tracing: ticks feeding the NEXT emitted sequence share
            # its trace id (sequence builders span many ticks)
            tick_tid = ptrace.maybe_trace("a", memory.emit_count + 1)
            with ptrace.span("act", tick_tid):
                if use_dstack:
                    with obs_run.span("act"):
                        actions, (pre_c, pre_h) = driver.act_frames(obs, prev_cuts)
                else:
                    with obs_run.span("act"):
                        actions, (pre_c, pre_h) = driver.act(stacker.push(obs))
            with ptrace.span("env_step", tick_tid):
                new_obs, rewards, terminals, truncs, ep_returns = env.step(
                    actions)
            cuts = terminals | truncs
            with ptrace.span("append", tick_tid):
                memory.append_batch(
                    obs, actions, rewards, terminals, pre_c, pre_h, truncations=truncs
                )
            driver.reset_lanes(cuts)
            if not use_dstack:
                stacker.reset_lanes(cuts)
            prev_cuts = cuts
            obs = new_obs
            frames += lanes_total  # global frames: hosts tick in lockstep
            for r in ep_returns[~np.isnan(ep_returns)]:
                returns.append(float(r))

            warm = (
                frames - frames_at_start >= frames_warm
                if multihost
                else len(memory) >= learn_start_seqs
            )
            if warm:
                if driver.wants_calibration():
                    # calibration from replay statistics: the first
                    # history_length consecutive frames of each sampled
                    # sequence, stacked into the act input shape (paired
                    # with the zero LSTM state the gate compares under).
                    # serve_quantize-on only, so the off-mode sampler RNG
                    # stream is untouched.
                    calib = memory.sample(
                        min(cfg.quant_calib_batch, cfg.batch_size),
                        priority_beta(cfg, frames),
                    )
                    h = min(cfg.history_length, calib.obs.shape[1])
                    driver.set_calibration(
                        np.moveaxis(calib.obs[:, :h, :, :, 0], 1, -1))
                if frontier is not None and prefetcher is None:
                    from rainbow_iqn_apex_tpu.utils.prefetch import (
                        SampleAheadPusher,
                    )

                    prefetcher = SampleAheadPusher(
                        frontier,
                        lambda idx, w: (
                            idx,
                            to_device_seq_batch(memory.assemble_idx(idx, w)),
                        ),
                        cfg.batch_size,
                        lambda: priority_beta(cfg, frames),
                        lambda: len(memory),
                        depth=cfg.sample_ahead_depth,
                        registry=obs_run.registry,
                    )
                elif cfg.prefetch_depth > 0 and prefetcher is None:
                    if multihost:
                        # host-side local sample only; the collective-bearing
                        # learn_local stays on the main thread
                        prefetcher = BatchPrefetcher(
                            lambda: (
                                (s := memory.sample(
                                    local_batch, priority_beta(cfg, frames)
                                )).idx,
                                s,
                            ),
                            depth=cfg.prefetch_depth,
                            device_put=False,
                            registry=obs_run.registry,
                        )
                    else:
                        prefetcher = BatchPrefetcher(
                            lambda: (
                                (s := memory.sample(
                                    cfg.batch_size, priority_beta(cfg, frames)
                                )).idx,
                                to_device_seq_batch(s),
                            ),
                            depth=cfg.prefetch_depth,
                            device_put=False,
                            registry=obs_run.registry,
                        )
                steps_due = frames // frames_per_step - driver.step
                for _ in range(max(steps_due, 0)):
                    if sup.snapshot_due(driver.step):
                        # drain first: the rollback target must never hold
                        # a step whose finiteness is still in flight
                        if not _drain():
                            continue
                        sup.snapshot_if_due(
                            driver.step,
                            lambda: (host_state(driver.state), driver.key),
                        )
                    ltid = ptrace.maybe_trace("l", driver.step + 1)
                    if multihost:
                        with ptrace.span("gather", ltid):
                            if prefetcher is not None:
                                idx, s = prefetcher.get()
                            else:
                                s = memory.sample(local_batch, priority_beta(cfg, frames))
                                idx = s.idx
                        links = ptrace.link_ids(
                            "a", memory.trace_ids(idx)) if ltid else ()
                        with ptrace.span("learn_step", ltid, links=links,
                                         step=driver.step + 1):
                            with obs_run.span("learn_step"):
                                info = driver.learn_local(
                                    sup.poison_maybe(s),
                                    global_size=len(memory) * nproc,
                                    beta=priority_beta(cfg, frames),
                                )
                    elif prefetcher is not None:
                        with ptrace.span("gather", ltid):
                            idx, batch = prefetcher.get()
                        # stamps read at dispatch, not the worker's sample —
                        # a lapped slot links one emit late; accepted for
                        # sampled telemetry (see apex.py's note)
                        links = ptrace.link_ids(
                            "a", memory.trace_ids(idx)) if ltid else ()
                        with ptrace.span("learn_step", ltid, links=links,
                                         step=driver.step + 1):
                            with obs_run.span("learn_step"):
                                info = driver.learn_batch(sup.poison_maybe(batch))
                    else:
                        with ptrace.span("replay_sample", ltid):
                            with obs_run.span("replay_sample"):
                                s = memory.sample(
                                    local_batch, priority_beta(cfg, frames)
                                )
                        idx, batch = s.idx, to_device_seq_batch(s)
                        links = ptrace.link_ids(
                            "a", memory.trace_ids(idx)) if ltid else ()
                        with ptrace.span("learn_step", ltid, links=links,
                                         step=driver.step + 1):
                            with obs_run.span("learn_step"):
                                info = driver.learn_batch(sup.poison_maybe(batch))
                    sup.maybe_stall()
                    if mt_learn_rows is not None:
                        # per-game learn share off the sequence slot lane
                        # stamps (telemetry; the `games` row reports it)
                        mt_learn_rows += np.bincount(
                            games_of_lane[memory.lane_of(idx)],
                            minlength=spec.num_games,
                        ).astype(np.int64)
                    # dispatch-only hot path; the deferred guard decision is
                    # still lockstep across hosts (all-reduced loss -> same
                    # in-graph finite flag), same argument as apex.py
                    if not _commit(ring.push(driver.step, idx, info)):
                        continue
                    step = driver.step
                    obs_run.after_learn_step(step)
                    if step - last_pub >= cfg.weight_publish_interval:
                        # ring boundary: actors never adopt params with an
                        # unverified step in their history
                        if not _drain():
                            continue
                        with obs_run.span("publish_weights"):
                            version = driver.publish_weights()
                        last_pub = step
                        obs_run.registry.gauge(
                            "weights_version", "learner"
                        ).set(version)
                        if heartbeat is not None:
                            heartbeat.set_weight_version(version)
                    if step % cfg.metrics_interval == 0:
                        fence.observe(
                            driver.actor_weights_version,
                            driver.weights_version,
                            step=step,
                        )
                        metrics.log(
                            "learn",
                            step=step,
                            frames=frames,
                            fps=metrics.fps(frames),
                            loss=last_scalars.get("loss", float("nan")),
                            q_mean=last_scalars.get("q_mean", float("nan")),
                            mean_return=float(np.mean(returns)) if returns else float("nan"),
                            sequences=len(memory),
                            staleness=step - last_pub,
                        )
                        obs_run.periodic(
                            step,
                            frames,
                            replay_size=len(memory),
                            replay_occupancy=round(
                                len(memory) / max(memory.capacity, 1), 4
                            ),
                            weight_staleness=step - last_pub,
                            weights_version=driver.weights_version,
                            weight_version_lag=fence.lag,
                            **pipeline_gauges(ring, obs_run.registry, frontier),
                        )
                        if spec is not None:
                            # per-game breakdown (the same `games` row the
                            # iqn apex driver emits; sequence replay is one
                            # tree, so per-game sizes come off the slot
                            # lane stamps instead of shard blocks).
                            # Occupancy is each game's fill of its FAIR
                            # SHARE (capacity / num_games) so the number
                            # means the same thing as the iqn driver's
                            # per-game-capacity fill: a balanced full
                            # buffer reads 1.0 per game; > 1.0 says the
                            # game is crowding its siblings out of the
                            # shared tree.
                            sizes = np.bincount(
                                games_of_lane[memory.slot_lanes()],
                                minlength=spec.num_games,
                            ).astype(np.int64)
                            total_rows = max(int(mt_learn_rows.sum()), 1)
                            fair = max(
                                memory.capacity / spec.num_games, 1.0)
                            metrics.log(
                                "games", step=step, frames=frames,
                                schedule="sequence",
                                **games_obs.row(
                                    learn_shares=mt_learn_rows / total_rows,
                                    learn_rows=mt_learn_rows,
                                    game_sizes=sizes,
                                    game_occupancy=sizes / fair,
                                ),
                            )
                        ptrace.emit_lag_row(step)
                        if monitor is not None:
                            # same lease-edge reporting as train_apex: one
                            # host_dead/host_alive row per lease epoch
                            dead, alive = monitor.poll()
                            for lease in dead:
                                metrics.log(
                                    "fault", event="host_dead",
                                    dead_host=lease.host, epoch=lease.epoch,
                                    step=step, frames=frames,
                                )
                            for lease in alive:
                                metrics.log(
                                    "host_alive", alive_host=lease.host,
                                    epoch=lease.epoch, step=step,
                                    frames=frames,
                                )
                    if cfg.eval_interval and step % cfg.eval_interval == 0:
                        # drain on EVERY host (lockstep cadence) so a
                        # rollback here can't diverge the pod; the eval
                        # itself stays main-host work
                        if not _drain():  # evaluate only verified params
                            continue
                        if is_main and spec is not None:
                            _eval_r2d2_multigame(
                                cfg, spec, env, driver, metrics, step,
                                games_obs)
                        elif is_main:
                            metrics.log(
                                "eval", step=step,
                                **_eval_r2d2_learner(cfg, env, driver),
                            )
                    if cfg.checkpoint_interval and step % cfg.checkpoint_interval == 0:
                        # collective under jax.distributed: every host joins,
                        # the primary writes (a p0-only call would hang);
                        # retry decisions are deterministic -> lockstep
                        if not _drain():  # checkpoint only verified params
                            continue
                        sup.save_checkpoint(
                            ckpt, step, host_state(driver.state),
                            {"frames": frames, "weights_version": driver.weights_version,
                             **rng_extra(driver.key)},
                        )
                        sup.save_replay(cfg, memory)
        # end of run: retire the in-flight tail before the final eval/save
        _drain()
    finally:
        if prefetcher is not None:
            prefetcher.close()
        sup.close()
        obs_run.close(driver.step, frames)
        if heartbeat is not None:
            heartbeat.stop()

    if is_main and spec is not None:
        final_eval = _eval_r2d2_multigame(
            cfg, spec, env, driver, metrics, driver.step, games_obs)
    elif is_main:
        final_eval = _eval_r2d2_learner(cfg, env, driver)
        metrics.log("eval", step=driver.step, **final_eval)
    else:
        final_eval = {}
    sup.save_checkpoint(
        ckpt, driver.step, host_state(driver.state),
        {"frames": frames, "weights_version": driver.weights_version,
                             **rng_extra(driver.key)}, critical=True,
    )
    if frontier is not None:
        # the final drain may have been skipped by a rollback: catch the
        # cold-path tree up before it is persisted
        frontier.reconcile()
    sup.save_replay(cfg, memory, critical=True)
    ckpt.wait()
    metrics.close()
    return {
        "frames": frames,
        "learn_steps": driver.step,
        "lanes": lanes_total,
        "sequences": len(memory),
        "train_return_mean": float(np.mean(returns)) if returns else float("nan"),
        "rollbacks": sup.rollbacks,
        "stalls": sup.stalls,
        "io_faults": sup.io_faults,
        **{f"eval_{k}": v for k, v in final_eval.items()},
    }
