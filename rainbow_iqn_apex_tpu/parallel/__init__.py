from rainbow_iqn_apex_tpu.parallel.apex import (
    ActorPriorityEstimator,
    ApexDriver,
    train_apex,
)
from rainbow_iqn_apex_tpu.parallel.apex_r2d2 import R2D2ApexDriver, train_apex_r2d2
from rainbow_iqn_apex_tpu.parallel.mesh import (
    actor_mesh,
    batch_sharding,
    learner_mesh,
    parse_mesh_shape,
    replicated,
    split_devices,
)
from rainbow_iqn_apex_tpu.parallel.sharded_replay import ShardedReplay

__all__ = [
    "ActorPriorityEstimator",
    "ApexDriver",
    "R2D2ApexDriver",
    "train_apex",
    "train_apex_r2d2",
    "ShardedReplay",
    "actor_mesh",
    "batch_sharding",
    "learner_mesh",
    "parse_mesh_shape",
    "replicated",
    "split_devices",
]
