"""parallel/ — meshes, apex drivers, sharded replay, and the elastic fleet.

Exports resolve lazily (PEP 562): the apex drivers pull in jax at import
time, but `parallel.elastic` and `parallel.sharded_replay` are deliberately
jax-free so respawned actor processes (scripts/chaos_soak.py,
RoleSupervisor children) can import them without paying the device-runtime
import tax.  An eager ``from .apex import ...`` here would defeat that —
importing any submodule executes this file first.
"""

from typing import TYPE_CHECKING

_EXPORTS = {
    "ActorPriorityEstimator": "rainbow_iqn_apex_tpu.parallel.apex",
    "ApexDriver": "rainbow_iqn_apex_tpu.parallel.apex",
    "train_apex": "rainbow_iqn_apex_tpu.parallel.apex",
    "R2D2ApexDriver": "rainbow_iqn_apex_tpu.parallel.apex_r2d2",
    "train_apex_r2d2": "rainbow_iqn_apex_tpu.parallel.apex_r2d2",
    "actor_mesh": "rainbow_iqn_apex_tpu.parallel.mesh",
    "batch_sharding": "rainbow_iqn_apex_tpu.parallel.mesh",
    "learner_mesh": "rainbow_iqn_apex_tpu.parallel.mesh",
    "parse_mesh_shape": "rainbow_iqn_apex_tpu.parallel.mesh",
    "replicated": "rainbow_iqn_apex_tpu.parallel.mesh",
    "split_devices": "rainbow_iqn_apex_tpu.parallel.mesh",
    "ShardedReplay": "rainbow_iqn_apex_tpu.parallel.sharded_replay",
    "StandbyLearner": "rainbow_iqn_apex_tpu.parallel.failover",
    "run_standby": "rainbow_iqn_apex_tpu.parallel.failover",
    "HeartbeatMonitor": "rainbow_iqn_apex_tpu.parallel.elastic",
    "HeartbeatWriter": "rainbow_iqn_apex_tpu.parallel.elastic",
    "Lease": "rainbow_iqn_apex_tpu.parallel.elastic",
    "RoleSupervisor": "rainbow_iqn_apex_tpu.parallel.elastic",
    "StalenessFence": "rainbow_iqn_apex_tpu.parallel.elastic",
    "WeightMailbox": "rainbow_iqn_apex_tpu.parallel.elastic",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name: str):
    module = _EXPORTS.get(name)
    if module is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(module), name)


def __dir__():
    return __all__


if TYPE_CHECKING:  # static analyzers see the eager imports
    from rainbow_iqn_apex_tpu.parallel.apex import (  # noqa: F401
        ActorPriorityEstimator,
        ApexDriver,
        train_apex,
    )
    from rainbow_iqn_apex_tpu.parallel.apex_r2d2 import (  # noqa: F401
        R2D2ApexDriver,
        train_apex_r2d2,
    )
    from rainbow_iqn_apex_tpu.parallel.elastic import (  # noqa: F401
        HeartbeatMonitor,
        HeartbeatWriter,
        Lease,
        RoleSupervisor,
        StalenessFence,
        WeightMailbox,
    )
    from rainbow_iqn_apex_tpu.parallel.mesh import (  # noqa: F401
        actor_mesh,
        batch_sharding,
        learner_mesh,
        parse_mesh_shape,
        replicated,
        split_devices,
    )
    from rainbow_iqn_apex_tpu.parallel.failover import (  # noqa: F401
        StandbyLearner,
        run_standby,
    )
    from rainbow_iqn_apex_tpu.parallel.sharded_replay import (  # noqa: F401
        ShardedReplay,
    )
