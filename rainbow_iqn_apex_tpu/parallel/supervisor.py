"""Train-loop supervision: NaN/Inf guard with rollback, stall watchdog,
retried checkpoint/replay IO.

Ape-X runs are long-lived by construction (arXiv:1803.00933): over days of
training the learner WILL see a poisoned batch (inf reward from a broken
env, NaN grads from an fp edge), checkpoint IO WILL flake (networked FS),
and a step WILL wedge (device stall, dead collective peer).  Before this
module, any one of those killed `train_apex` outright.  The supervisor
turns them into bounded, reported events:

- **NaN/Inf guard**: every learn step's finiteness is checked.  The hot
  loops compute the flag IN-GRAPH (``info["finite"]``, ops/learn.py) and
  defer the host read to the write-back ring boundary
  (``retire_ok`` — utils/writeback.py), so the guard adds no per-step
  device round-trip; ``step_ok`` remains the synchronous form for loops
  that already hold host scalars (anakin's segment results, tests).  A
  non-finite step rolls params + optimizer state + RNG back to the
  last-good in-memory snapshot and skips the poisoned batch's priority
  write-back — with a ring in flight the caller also quarantines every
  in-flight idx set, and the snapshot is only ever captured at a drain
  point so it can never contain an unverified step; ``max_nan_strikes``
  consecutive bad steps abort the run (`TrainAborted`) — rollback can mask
  a transient, not a systemically poisoned replay.
- **Stall watchdog**: a daemon thread that fires when no learn step
  completes within ``stall_timeout_s`` — the signal a wedged collective or
  device gives you nothing else for.  Detection is reporting (metrics row +
  counter); a Python thread cannot interrupt a blocked XLA dispatch, so the
  watchdog's job is making the stall visible to the harness watching the
  metrics stream.
- **Retried IO**: checkpoint saves and replay snapshots run under the shared
  bounded backoff-with-jitter policy (utils/faults.RetryPolicy — the same
  policy serving's hot-swap uses).  Interval saves that exhaust the budget
  degrade to a reported fault (training is the product; durability is
  best-effort mid-run); the final save at exit is critical and re-raises.

Multi-host note: the guard's decision is identical on every host — the
loss is all-reduced by the dp-sharded learn step, and the rollback snapshot
is a host copy of the replicated state — so rollback never diverges the
SPMD program (divergent control flow around a collective deadlocks a pod).
"""

from __future__ import annotations

import dataclasses
import math
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import numpy as np

from rainbow_iqn_apex_tpu.utils import faults, hostsync


class TrainAborted(RuntimeError):
    """Too many consecutive non-finite learn steps; rollback cannot help."""


class StallWatchdog:
    """Fires ``on_stall(elapsed_s)`` when ``tick()`` goes quiet for longer
    than ``timeout_s``.  One firing per stall episode (re-arms on the next
    tick).  The thread starts lazily at the first tick so jit compilation
    of the first step never counts as a stall."""

    def __init__(
        self,
        timeout_s: float,
        on_stall: Callable[[float], None],
        poll_s: Optional[float] = None,
    ):
        self.timeout_s = float(timeout_s)
        self.on_stall = on_stall
        self.poll_s = poll_s if poll_s is not None else max(timeout_s / 4.0, 0.05)
        self.stalls = 0
        self._last: Optional[float] = None
        self._fired = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()

    def tick(self) -> None:
        with self._lock:
            self._last = time.monotonic()
            self._fired = False
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._run, name="stall-watchdog", daemon=True
                )
                self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            with self._lock:
                if self._last is None or self._fired:
                    continue
                elapsed = time.monotonic() - self._last
                if elapsed < self.timeout_s:
                    continue
                self._fired = True
                self.stalls += 1
            try:
                self.on_stall(elapsed)
            except Exception:
                pass  # a broken reporter must not kill the watchdog

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


class TrainSupervisor:
    """Wraps a train loop's learn-step sequence with fault handling.

    The loop stays explicit; the supervisor is called at four seams:

        sup.snapshot_if_due(step, lambda: (host_state(...), key))
        batch = sup.poison_maybe(batch)          # chaos: nan_loss point
        info = <learn step>; sup.maybe_stall()   # chaos: stalled_step point
        if sup.step_ok(info): <priority write-back, metrics, publish>
        else: driver.load_snapshot(*sup.rollback())

    plus retried IO: ``sup.save_checkpoint(...)`` / ``sup.save_replay(...)``.
    """

    def __init__(
        self,
        cfg,
        metrics=None,
        injector: Optional[faults.FaultInjector] = None,
        registry=None,
    ):
        self.cfg = cfg
        self.metrics = metrics
        # obs/ wiring: live supervisor gauges (strikes/rollbacks/stalls/IO
        # faults) for /metrics scrapes.  Fault *counters* are folded from the
        # fault rows by obs.health (the MetricsLogger observer), so the row
        # funnel stays the single source and nothing double-counts.
        self.registry = registry
        self.injector = injector if injector is not None else faults.get()
        self.policy = faults.RetryPolicy.from_config(cfg)
        self.max_nan_strikes = int(cfg.max_nan_strikes)
        self.snapshot_interval = max(int(cfg.guard_snapshot_interval), 1)
        self.strikes = 0  # consecutive non-finite steps
        self.rollbacks = 0
        self.io_faults = 0
        self._snap: Optional[Tuple[int, Any, Any]] = None  # (step, state, key)
        self.watchdog: Optional[StallWatchdog] = None
        if cfg.stall_timeout_s > 0:
            self.watchdog = StallWatchdog(cfg.stall_timeout_s, self._on_stall)

    # ------------------------------------------------------------- reporting
    def _report(self, event: str, **fields) -> None:
        if self.metrics is not None:
            self.metrics.log("fault", event=event, **fields)
        if self.registry is not None:
            self.registry.gauge("nan_strikes", "supervisor").set(self.strikes)
            self.registry.gauge("rollbacks", "supervisor").set(self.rollbacks)
            self.registry.gauge("stalls", "supervisor").set(self.stalls)
            self.registry.gauge("io_faults", "supervisor").set(self.io_faults)

    def _on_stall(self, elapsed: float) -> None:
        self._report("stalled_step", elapsed_s=round(elapsed, 3))

    @property
    def stalls(self) -> int:
        return self.watchdog.stalls if self.watchdog is not None else 0

    # ------------------------------------------------------------- snapshots
    def snapshot_due(self, step: int) -> bool:
        """True when ``snapshot_if_due(step, ...)`` would capture.  Pipelined
        loops check this FIRST and drain their write-back ring before
        capturing, so the snapshot can never contain an unverified step."""
        return self._snap is None or step - self._snap[0] >= self.snapshot_interval

    def snapshot_if_due(self, step: int, capture: Callable[[], Tuple[Any, Any]]) -> bool:
        """Refresh the last-good (state, key) host copy every
        ``guard_snapshot_interval`` learner steps.  ``capture`` must return
        host-materialisable values (the caller passes ``host_state(...)``);
        the materialization is a sanctioned sync (snapshot cadence, not the
        per-step hot path)."""
        if not self.snapshot_due(step):
            return False
        with hostsync.sanctioned():
            state, key = capture()
            self._snap = (step, jax.tree.map(np.asarray, state), np.asarray(key))
        return True

    def rollback(self) -> Tuple[Any, Any]:
        """The last-good (state, key); counts a strike, raises
        ``TrainAborted`` past the budget.  Caller re-places onto its mesh."""
        self.rollbacks += 1
        if self._snap is None:
            self._report("train_aborted", reason="no_snapshot")
            raise TrainAborted(
                "non-finite learn step before any good snapshot existed"
            )
        if self.strikes >= self.max_nan_strikes:
            self._report("train_aborted", reason="strike_budget",
                         strikes=self.strikes)
            raise TrainAborted(
                f"{self.strikes} consecutive non-finite learn steps "
                f"(budget {self.max_nan_strikes}); replay looks poisoned"
            )
        step, state, key = self._snap
        self._report("rollback", to_step=step, strikes=self.strikes)
        return state, key

    # ------------------------------------------------------------ step guard
    def step_ok(self, info: Dict[str, Any]) -> bool:
        """True when the step's loss/grad-norm are finite.  Ticks the stall
        watchdog (a completed step IS the liveness signal).  Synchronous
        form: floats the scalars here (one device->host sync when they are
        still device arrays) — the pipelined loops use ``retire_ok``."""
        if self.watchdog is not None:
            self.watchdog.tick()
        with hostsync.sanctioned():
            loss = float(info["loss"])
            grad = float(info["grad_norm"]) if "grad_norm" in info else 0.0
        return self._finite_ok(loss, grad, math.isfinite(loss) and math.isfinite(grad))

    def retire_ok(self, retired) -> bool:
        """Deferred step guard for the write-back ring (utils/writeback.py):
        the finiteness flag was computed in-graph K steps ago and
        materialized at the ring boundary, so this touches no device value.
        On False the caller must quarantine EVERY in-flight idx set (the
        retired entry's and the ring's flush()) before rolling back."""
        if self.watchdog is not None:
            self.watchdog.tick()
        loss = retired.scalars.get("loss", float("nan"))
        grad = retired.scalars.get("grad_norm", 0.0)
        return self._finite_ok(loss, grad, bool(retired.finite), step=retired.step,
                               lag=retired.lag)

    def _finite_ok(self, loss: float, grad: float, finite: bool, **extra) -> bool:
        if finite:
            self.strikes = 0
            return True
        self.strikes += 1
        self._report(
            "nonfinite_step",
            loss=loss if math.isfinite(loss) else str(loss),
            grad_norm=grad if math.isfinite(grad) else str(grad),
            strikes=self.strikes,
            **extra,
        )
        return False

    # ---------------------------------------------------------------- chaos
    def poison_maybe(self, batch):
        """nan_loss injection point: when armed, returns a copy of the batch
        with non-finite rewards (the shape a broken env/replay corruption
        actually produces), so the guard's detection path is exercised end
        to end.  Disarmed: returns the batch untouched."""
        if not self.injector.enabled or not self.injector.fire("nan_loss"):
            return batch
        self._report("injected_nan_batch")
        reward = batch.reward
        try:  # device array (prefetched Batch) or host ndarray (SampledBatch)
            poisoned = reward * float("nan")
        except TypeError:
            poisoned = np.asarray(reward) * np.nan
        return dataclasses.replace(batch, reward=poisoned)

    def maybe_stall(self) -> None:
        """stalled_step injection point: block for cfg.fault_stall_s, as a
        wedged device dispatch would."""
        if self.injector.enabled and self.injector.fire("stalled_step"):
            self._report("injected_stall", seconds=self.cfg.fault_stall_s)
            time.sleep(self.cfg.fault_stall_s)

    # ------------------------------------------------------------ retried IO
    def _retry(self, what: str, fn: Callable, critical: bool) -> bool:
        def on_retry(attempt: int, exc: BaseException) -> None:
            self.io_faults += 1
            self._report(
                "io_retry",
                what=what,
                attempt=attempt,
                error=f"{type(exc).__name__}: {exc}"[:200],
            )

        try:
            faults.retry_call(
                fn, self.policy, retry_on=(OSError, IOError), on_retry=on_retry
            )
            return True
        except (OSError, IOError) as e:
            if critical:
                raise
            self._report(
                "io_failed", what=what, error=f"{type(e).__name__}: {e}"[:200]
            )
            return False

    def save_checkpoint(
        self, ckpt, step: int, state, extra: Optional[Dict[str, Any]] = None,
        critical: bool = False,
    ) -> bool:
        """Checkpointer.save under the shared retry policy.  Interval saves
        (critical=False) degrade to a reported fault on exhaustion; the
        final save at exit should pass critical=True."""
        return self._retry(
            "checkpoint", lambda: ckpt.save(step, state, extra), critical
        )

    def save_replay(self, cfg, memory, critical: bool = False) -> bool:
        from rainbow_iqn_apex_tpu.utils.checkpoint import save_replay_snapshot

        return self._retry(
            "replay_snapshot", lambda: save_replay_snapshot(cfg, memory), critical
        )

    def close(self) -> None:
        if self.watchdog is not None:
            self.watchdog.stop()
