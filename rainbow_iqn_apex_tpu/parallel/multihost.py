"""Multi-host scaffolding: one SPMD program per pod host.

Parity: the reference scales by pointing more actor *processes* (possibly on
other machines) at shared Redis servers (SURVEY.md §2 rows 6-7).  The
TPU-native multi-host shape (north star BASELINE.json:5) keeps the same
topology but swaps the transport:

  reference                       multi-host here
  ----------------------------    ------------------------------------------
  redis-server per shard host     one replay shard in each host's DRAM
  actors dial their shard         each host's env lanes append LOCALLY
  learner fetches over TCP        each host feeds the dp-sharded learn step
                                  its LOCAL sub-batch (jax.make_array_from_
                                  single_device_arrays); the gradient
                                  all-reduce over ICI/DCN is the only
                                  cross-host traffic XLA inserts
  weight mailbox over TCP         params already replicated by the mesh

`initialize()` wraps jax.distributed.initialize; `host_lanes`/`host_shard`
carve the global lane/shard space by process index.  apex.train_apex runs
this topology end-to-end when cfg.process_count > 1 (every host executes the
same loop; see docs/RUNBOOK.md "Multi-host Ape-X").  CI exercises it with
two REAL processes over a CPU Gloo fabric (tests/test_multihost.py): learn
numerics are asserted identical to a single-process run, and a toy train
runs end-to-end.  Real pods swap the fabric for ICI/DCN with no code change.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from rainbow_iqn_apex_tpu.utils import faults


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Initialise the JAX distributed runtime (no-op if single-process args).

    On TPU pods the three arguments are inferred from the environment; on
    CPU/GPU clusters pass them explicitly (reference parity: the redis
    host/port CLI flags, SURVEY §2 row 1, become the coordinator address).
    """
    if num_processes is not None and num_processes <= 1:
        return
    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    jax.distributed.initialize(**kwargs)


@dataclasses.dataclass(frozen=True)
class HostTopology:
    process_id: int
    process_count: int
    local_devices: int
    global_devices: int

    @classmethod
    def current(cls) -> "HostTopology":
        return cls(
            process_id=jax.process_index(),
            process_count=jax.process_count(),
            local_devices=jax.local_device_count(),
            global_devices=jax.device_count(),
        )

    def host_lanes(self, lanes_total: int) -> Tuple[int, int]:
        """This host's [start, end) slice of the global env-lane space."""
        if lanes_total % self.process_count:
            raise ValueError(
                f"{lanes_total} lanes do not divide over {self.process_count} hosts"
            )
        per = lanes_total // self.process_count
        return self.process_id * per, (self.process_id + 1) * per

    def host_shard(self, num_shards: int) -> int:
        """Replay shard owned by this host (one shard per host by default)."""
        if num_shards % self.process_count:
            raise ValueError(
                f"{num_shards} shards do not divide over {self.process_count} hosts"
            )
        return self.process_id * (num_shards // self.process_count)


@dataclasses.dataclass(frozen=True)
class HostPlan:
    """One host's carve of an apex run (shared by both apex trainers)."""

    multihost: bool
    nproc: int
    lanes: int  # this host's env lanes
    lane_lo: int  # global index of this host's first lane (seed offset)
    is_main: bool  # process 0: metrics/eval owner
    local_batch: int  # rows this host feeds into the dp-sharded learn step


def plan_hosts(cfg, lanes_total: int) -> HostPlan:
    """Validate the multi-host topology and carve this host's share.

    Single-process configs pass through untouched.  Multi-host requires
    jax.distributed to be initialized (process counts must agree),
    learner_devices == 0 (every chip plays both roles so the weight publish
    stays host-local), and lanes/batch divisible over the hosts.
    """
    nproc = max(cfg.process_count, 1)
    if nproc == 1:
        return HostPlan(False, 1, lanes_total, 0, True, cfg.batch_size)
    topo = HostTopology.current()
    if topo.process_count != nproc:
        raise RuntimeError(
            f"jax.distributed reports {topo.process_count} processes but "
            f"config says {nproc}; call multihost.initialize first"
        )
    if cfg.learner_devices:
        raise ValueError(
            "multi-host apex needs learner_devices=0 (every chip plays "
            "both roles) so the weight publish stays host-local"
        )
    if lanes_total % nproc or cfg.batch_size % nproc:
        raise ValueError(
            f"lanes ({lanes_total}) and batch_size ({cfg.batch_size}) "
            f"must divide over {nproc} hosts"
        )
    lane_lo, lane_hi = topo.host_lanes(lanes_total)
    return HostPlan(
        True, nproc, lane_hi - lane_lo, lane_lo,
        topo.process_id == 0, cfg.batch_size // nproc,
    )


# ------------------------------------------------------------- heartbeats
# Multi-host degradation (docs/RESILIENCE.md): a preempted actor host stops
# making progress silently — the survivors' next collective just hangs.  The
# only cross-host channel that needs no collective is the shared filesystem
# the run already writes to, so liveness is a per-host heartbeat FILE: each
# host re-writes ``heartbeats/h<i>.json`` on an interval, and any host can
# cheaply detect a peer whose file has gone stale.  Detection is the part a
# hung collective cannot give you; the report (a ``host_dead`` metrics row
# naming the host) is what lets an external supervisor restart or reshard
# the run instead of letting it wedge until the job timeout.


def heartbeat_dir(cfg) -> str:
    return os.path.join(cfg.results_dir, cfg.run_id, "heartbeats")


class HeartbeatWriter:
    """Daemon thread re-writing this host's heartbeat file every
    ``interval_s``.  Writes are atomic (tmp + rename) so a reader never sees
    a torn JSON.  The ``heartbeat_loss`` fault point suppresses writes —
    a preempted host, manufactured."""

    def __init__(self, directory: str, process_id: int, interval_s: float,
                 injector: Optional[faults.FaultInjector] = None):
        self.directory = directory
        self.process_id = int(process_id)
        self.interval_s = float(interval_s)
        self.injector = injector if injector is not None else faults.get()
        self.path = os.path.join(directory, f"h{process_id}.json")
        self.payload: Dict = {}  # callers may stuff step/frames in here
        self.beats = 0
        self.suppressed = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def beat(self) -> None:
        """One heartbeat write (also usable inline, without the thread)."""
        if self.injector.enabled and self.injector.fire("heartbeat_loss"):
            self.suppressed += 1
            return
        os.makedirs(self.directory, exist_ok=True)
        row = {
            "process_id": self.process_id,
            "t_mono": time.monotonic(),
            "t_wall": time.time(),
            **self.payload,
        }
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(row, f)
        os.replace(tmp, self.path)
        self.beats += 1

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.beat()
            except OSError:
                pass  # a flaky FS write is itself a missed beat; keep going
            self._stop.wait(self.interval_s)

    def start(self) -> "HeartbeatWriter":
        if self._thread is None:
            self.beat()  # first beat synchronously: exists before any check
            self._thread = threading.Thread(
                target=self._run, name="heartbeat-writer", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


class HeartbeatMonitor:
    """Scan peer heartbeat files; report hosts gone stale past ``timeout_s``.

    Staleness is judged by file mtime (monotonic-ish on one filesystem and
    immune to clock skew between hosts writing wall-clock payloads).  A host
    with NO file yet is not dead — it may simply not have started; only a
    file that existed and stopped updating is a death signal.  ``check()``
    returns the CURRENT dead set; ``newly_dead()`` returns only hosts that
    died since the last call (the edge, for once-per-transition reporting).
    """

    def __init__(self, directory: str, timeout_s: float, self_id: Optional[int] = None):
        self.directory = directory
        self.timeout_s = float(timeout_s)
        self.self_id = self_id
        self._reported: set = set()

    def ages(self) -> Dict[int, float]:
        """host id -> seconds since its heartbeat file was last written."""
        out: Dict[int, float] = {}
        try:
            names = os.listdir(self.directory)
        except FileNotFoundError:
            return out
        now = time.time()
        for name in names:
            if not (name.startswith("h") and name.endswith(".json")):
                continue
            try:
                hid = int(name[1:-5])
                out[hid] = now - os.path.getmtime(os.path.join(self.directory, name))
            except (ValueError, OSError):
                continue  # torn tmp file or a peer mid-rename
        return out

    def check(self) -> List[int]:
        """All hosts currently considered dead (stale past timeout)."""
        return sorted(
            hid
            for hid, age in self.ages().items()
            if age > self.timeout_s and hid != self.self_id
        )

    def newly_dead(self) -> List[int]:
        dead = set(self.check())
        fresh = sorted(dead - self._reported)
        # a host that comes BACK (file re-written) re-arms its edge report
        self._reported = dead
        return fresh


# --------------------------------------------------------- shared SPMD helpers
# Used by BOTH apex drivers (feedforward and recurrent) so the multi-host
# semantics can never drift between them.
def local_rows(arr: jax.Array) -> np.ndarray:
    """This process's rows of a leading-axis-sharded global array, in global
    row order (= the order of the local data this process contributed via
    ``make_array_from_process_local_data``)."""
    shards = sorted(arr.addressable_shards, key=lambda s: s.index[0].start or 0)
    return np.concatenate([np.asarray(s.data) for s in shards])


def host_state(tree):
    """A checkpoint-safe view of a (replicated) train-state tree: multi-host
    global arrays are pulled to host NumPy (every process holds a replica)
    so Orbax is never asked to gather non-addressable shards; anything fully
    addressable passes through untouched."""
    leaf = jax.tree.leaves(tree)[0]
    if hasattr(leaf, "is_fully_addressable") and not leaf.is_fully_addressable:
        return jax.tree.map(np.asarray, tree)
    return tree


def make_global_is_weights(batch_sh):
    """jit: w = (N q)^-beta max-normalized over the GLOBAL dp-sharded batch
    (the cross-host max is one tiny collective).  The N*q product arrives
    pre-multiplied per row — see ``global_is_nq`` — so no host-varying
    scalar is ever passed as a replicated operand."""
    return jax.jit(
        lambda nq, beta: (lambda w: (w / w.max()).astype(jnp.float32))(
            jnp.maximum(nq, 1e-12) ** (-beta)
        ),
        in_shardings=(batch_sh, None),
        out_shardings=batch_sh,
    )


def global_is_nq(prob: np.ndarray, global_size: float) -> np.ndarray:
    """Per-row N*q for ``make_global_is_weights``: the fixed per-host batch
    quota makes the sampling scheme a uniform mixture over hosts, so the
    global sample probability of a local row is prob_local / n_hosts."""
    return global_size * np.asarray(prob) / jax.process_count()


def lane_put(lane_sh):
    """host rows -> lane-sharded device array (single- or multi-host; with
    one process this is just a device_put onto the actor mesh)."""

    def put(x: np.ndarray):
        return jax.make_array_from_process_local_data(
            lane_sh, np.ascontiguousarray(x)
        )

    return put


def shift_stack(stack, frame, keep):
    """Device-resident frame-stack update shared by both apex drivers:
    zero the stacks of lanes whose episode was cut LAST tick (matching the
    host FrameStacker's push-then-reset ordering), then shift the newest
    [L, H, W] frame into the trailing channel."""
    stack = stack * keep[:, None, None, None].astype(stack.dtype)
    return jnp.concatenate([stack[..., 1:], frame[..., None]], axis=-1)
