"""Multi-host scaffolding: one SPMD program per pod host.

Parity: the reference scales by pointing more actor *processes* (possibly on
other machines) at shared Redis servers (SURVEY.md §2 rows 6-7).  The
TPU-native multi-host shape (north star BASELINE.json:5) keeps the same
topology but swaps the transport:

  reference                       multi-host here
  ----------------------------    ------------------------------------------
  redis-server per shard host     one replay shard in each host's DRAM
  actors dial their shard         each host's env lanes append LOCALLY
  learner fetches over TCP        each host feeds the dp-sharded learn step
                                  its LOCAL sub-batch (jax.make_array_from_
                                  single_device_arrays); the gradient
                                  all-reduce over ICI/DCN is the only
                                  cross-host traffic XLA inserts
  weight mailbox over TCP         params already replicated by the mesh

`initialize()` wraps jax.distributed.initialize; `host_lanes`/`host_shard`
carve the global lane/shard space by process index.  apex.train_apex runs
this topology end-to-end when cfg.process_count > 1 (every host executes the
same loop; see docs/RUNBOOK.md "Multi-host Ape-X").  CI exercises it with
two REAL processes over a CPU Gloo fabric (tests/test_multihost.py): learn
numerics are asserted identical to a single-process run, and a toy train
runs end-to-end.  Real pods swap the fabric for ICI/DCN with no code change.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Initialise the JAX distributed runtime (no-op if single-process args).

    On TPU pods the three arguments are inferred from the environment; on
    CPU/GPU clusters pass them explicitly (reference parity: the redis
    host/port CLI flags, SURVEY §2 row 1, become the coordinator address).
    """
    if num_processes is not None and num_processes <= 1:
        return
    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    jax.distributed.initialize(**kwargs)


@dataclasses.dataclass(frozen=True)
class HostTopology:
    process_id: int
    process_count: int
    local_devices: int
    global_devices: int

    @classmethod
    def current(cls) -> "HostTopology":
        return cls(
            process_id=jax.process_index(),
            process_count=jax.process_count(),
            local_devices=jax.local_device_count(),
            global_devices=jax.device_count(),
        )

    def host_lanes(self, lanes_total: int) -> Tuple[int, int]:
        """This host's [start, end) slice of the global env-lane space."""
        if lanes_total % self.process_count:
            raise ValueError(
                f"{lanes_total} lanes do not divide over {self.process_count} hosts"
            )
        per = lanes_total // self.process_count
        return self.process_id * per, (self.process_id + 1) * per

    def host_shard(self, num_shards: int) -> int:
        """Replay shard owned by this host (one shard per host by default)."""
        if num_shards % self.process_count:
            raise ValueError(
                f"{num_shards} shards do not divide over {self.process_count} hosts"
            )
        return self.process_id * (num_shards // self.process_count)


@dataclasses.dataclass(frozen=True)
class HostPlan:
    """One host's carve of an apex run (shared by both apex trainers)."""

    multihost: bool
    nproc: int
    lanes: int  # this host's env lanes
    lane_lo: int  # global index of this host's first lane (seed offset)
    is_main: bool  # process 0: metrics/eval owner
    local_batch: int  # rows this host feeds into the dp-sharded learn step


def plan_hosts(cfg, lanes_total: int) -> HostPlan:
    """Validate the multi-host topology and carve this host's share.

    Single-process configs pass through untouched.  Multi-host requires
    jax.distributed to be initialized (process counts must agree),
    learner_devices == 0 (every chip plays both roles so the weight publish
    stays host-local), and lanes/batch divisible over the hosts.
    """
    nproc = max(cfg.process_count, 1)
    if nproc == 1:
        return HostPlan(False, 1, lanes_total, 0, True, cfg.batch_size)
    topo = HostTopology.current()
    if topo.process_count != nproc:
        raise RuntimeError(
            f"jax.distributed reports {topo.process_count} processes but "
            f"config says {nproc}; call multihost.initialize first"
        )
    if cfg.learner_devices:
        raise ValueError(
            "multi-host apex needs learner_devices=0 (every chip plays "
            "both roles) so the weight publish stays host-local"
        )
    if lanes_total % nproc or cfg.batch_size % nproc:
        raise ValueError(
            f"lanes ({lanes_total}) and batch_size ({cfg.batch_size}) "
            f"must divide over {nproc} hosts"
        )
    lane_lo, lane_hi = topo.host_lanes(lanes_total)
    return HostPlan(
        True, nproc, lane_hi - lane_lo, lane_lo,
        topo.process_id == 0, cfg.batch_size // nproc,
    )


# ------------------------------------------------------------- heartbeats
# Multi-host degradation (docs/RESILIENCE.md): a preempted actor host stops
# making progress silently — the survivors' next collective just hangs.  The
# only cross-host channel that needs no collective is the shared filesystem
# the run already writes to, so liveness is a per-host heartbeat FILE.  The
# writer/monitor pair grew into a role-lease registry (payload carries role,
# shard, lease epoch, weight_version; the monitor reports host_dead AND
# host_alive edges once per epoch) and moved to parallel/elastic.py so
# respawned actor processes can import it without paying the jax import;
# re-exported here because this is where every existing caller found it.
from rainbow_iqn_apex_tpu.parallel.elastic import (  # noqa: F401,E402
    HeartbeatMonitor,
    HeartbeatWriter,
    Lease,
    heartbeat_dir,
)


# --------------------------------------------------------- shared SPMD helpers
# Used by BOTH apex drivers (feedforward and recurrent) so the multi-host
# semantics can never drift between them.
def local_rows(arr: jax.Array) -> np.ndarray:
    """This process's rows of a leading-axis-sharded global array, in global
    row order (= the order of the local data this process contributed via
    ``make_array_from_process_local_data``)."""
    shards = sorted(arr.addressable_shards, key=lambda s: s.index[0].start or 0)
    return np.concatenate([np.asarray(s.data) for s in shards])


def host_state(tree):
    """A checkpoint-safe view of a (replicated) train-state tree: multi-host
    global arrays are pulled to host NumPy (every process holds a replica)
    so Orbax is never asked to gather non-addressable shards; anything fully
    addressable passes through untouched."""
    leaf = jax.tree.leaves(tree)[0]
    if hasattr(leaf, "is_fully_addressable") and not leaf.is_fully_addressable:
        return jax.tree.map(np.asarray, tree)
    return tree


def make_global_is_weights(batch_sh):
    """jit: w = (N q)^-beta max-normalized over the GLOBAL dp-sharded batch
    (the cross-host max is one tiny collective).  The N*q product arrives
    pre-multiplied per row — see ``global_is_nq`` — so no host-varying
    scalar is ever passed as a replicated operand."""
    return jax.jit(
        lambda nq, beta: (lambda w: (w / w.max()).astype(jnp.float32))(
            jnp.maximum(nq, 1e-12) ** (-beta)
        ),
        in_shardings=(batch_sh, None),
        out_shardings=batch_sh,
    )


def global_is_nq(prob: np.ndarray, global_size: float) -> np.ndarray:
    """Per-row N*q for ``make_global_is_weights``: the fixed per-host batch
    quota makes the sampling scheme a uniform mixture over hosts, so the
    global sample probability of a local row is prob_local / n_hosts."""
    return global_size * np.asarray(prob) / jax.process_count()


def lane_put(lane_sh):
    """host rows -> lane-sharded device array (single- or multi-host; with
    one process this is just a device_put onto the actor mesh)."""

    def put(x: np.ndarray):
        return jax.make_array_from_process_local_data(
            lane_sh, np.ascontiguousarray(x)
        )

    return put


def shift_stack(stack, frame, keep):
    """Device-resident frame-stack update shared by both apex drivers:
    zero the stacks of lanes whose episode was cut LAST tick (matching the
    host FrameStacker's push-then-reset ordering), then shift the newest
    [L, H, W] frame into the trailing channel."""
    stack = stack * keep[:, None, None, None].astype(stack.dtype)
    return jnp.concatenate([stack[..., 1:], frame[..., None]], axis=-1)
