"""Epoch-fenced hot-standby learner failover (docs/RESILIENCE.md).

Ape-X centralizes all gradient work in ONE learner (arXiv:1803.00933).
After the elastic layer every actor self-heals, the replay fabric drops and
readmits shards, and the replay-net servers readmit — but a dead learner
host still killed the run: the only recovery was launch_apex.sh's external
restart loop, which loses the warm replay plane and every downstream
consumer mid-flight.  This module closes that last single point of failure
with a standby learner and a learner-role epoch fence:

- `StandbyLearner` tails the active learner's elastic lease (a
  `HeartbeatMonitor` over the same heartbeat dir).  On lease expiry it
  claims the learner role at ``learner_epoch + 1`` via the O_EXCL per-epoch
  claim files (`claim_role_epoch`): two racing standbys resolve to exactly
  one winner at the filesystem.  The winner runs the injected ``takeover``
  callback — the jax-heavy half (Checkpointer.restore_latest_valid, the
  CRC-verified replay snapshot, the resumed train loop) lives in the
  CALLER, keeping this module jax-free — and the successor publishes
  weights at versions strictly above the deceased learner's, so
  `StalenessFence`/`WeightMailbox`/`FleetRollout` consumers converge onto
  it without adopting anything stale.  The loser emits a reasoned
  ``failover`` row and re-arms as the NEW learner's standby — and while
  the winner is mid-restore (its learner-role lease not yet written) the
  loser **holds off**: a claim marker above every lease it has ever seen
  reads as "takeover in progress" (``holdoff`` row), and only a claimant
  silent past ``failover_takeover_deadline_s`` reopens the race.  The
  winner shortens that window to one beat by flipping its own lease to
  role=learner at the new epoch the instant the claim lands
  (``lease_writer``), so exactly one learner exists at every point of the
  protocol, not just at the O_EXCL file.
- **Zombie fencing**: a paused-not-dead learner (GC stall, network
  partition) that wakes after takeover carries a superseded
  ``learner_epoch``.  Every publish surface it touches — the driver
  publish (`QuantPublishMixin.attach_epoch_fence`), mailbox rows
  (`WeightMailbox.publish(learner_epoch=...)`, authoritative on disk),
  priority write-backs and replay-net snapshots (replay/net), the league
  outbox — checks an `EpochFence` (refreshed from the claim markers via
  `refresh_fence`) and REFUSES with ``failover`` event=fenced_stale
  instead of clobbering the successor.
- Standby modes: **cold** (claim -> restore, MTTR measured from the
  observed death to the takeover callback returning) and **warm**
  (``failover_warm``: a `MailboxSubscriber` keeps a bit-exact
  reconstruction of the freshest published params current, handed to the
  takeover callback so restore only replays the delta since the last
  checkpoint).

jax-free by construction (analysis/imports.py declares it): the idle
standby pays no device-runtime import tax.  All behavior is behind the
default-off ``failover_*`` config; with it off no learner epoch above 0
ever exists, so every fence check is identically False and the training
path is bitwise the pre-failover behaviour (tier-1 asserted).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from rainbow_iqn_apex_tpu.parallel.elastic import (
    EpochFence,
    HeartbeatMonitor,
    HeartbeatWriter,
    Lease,
    MailboxSubscriber,
    WeightMailbox,
    claim_role_epoch,
    heartbeat_dir,
    latest_role_epoch,
)
from rainbow_iqn_apex_tpu.utils import faults

# The one logical role the claim markers are keyed by (``learner.e<k>`` in
# the heartbeat dir) — role-keyed, not host-keyed, because the racers are
# different processes with different pids claiming one role.
LEARNER_ROLE = "learner"


def learner_epoch_at_start(cfg) -> int:
    """The learner-role epoch a STARTING learner claims and trains under.

    With failover off this is identically 0 and nothing is written — the
    off path stays bitwise.  With failover on the learner claims
    ``latest + 1`` (first launch: 0) through the same O_EXCL markers the
    standbys race, so a scheduler double-launch of the learner resolves to
    two different epochs — the younger one fences the elder's publishes."""
    if not getattr(cfg, "failover_standby", False):
        return 0
    directory = heartbeat_dir(cfg)
    while True:
        epoch = latest_role_epoch(directory, LEARNER_ROLE) + 1
        if claim_role_epoch(directory, LEARNER_ROLE, epoch):
            return epoch


def refresh_fence(fence: EpochFence, directory: str,
                  role: str = LEARNER_ROLE) -> int:
    """Latch the highest role epoch ever CLAIMED into ``fence``.

    This is how a zombie learns it was superseded: the claim markers are
    plain files, visible to a process that was paused through the whole
    takeover the moment it wakes — no message delivery required.  Returns
    the latched epoch."""
    return fence.observe(latest_role_epoch(directory, role))


class StandbyLearner:
    """Tail the learner's lease; claim the role at epoch+1 when it expires.

    Single responsibility split: this class owns detection, the claim race,
    warm-params tailing and the ``failover`` row surface; the jax-heavy
    recovery is the injected ``takeover(learner_epoch, warm_params)``
    callable, which should restore the newest VALID checkpoint
    (`Checkpointer.restore_latest_valid` — it scans past a torn newest
    step), restore the replay snapshot, and resume training publishing at
    versions strictly above the predecessor's.  Its return value is
    surfaced as ``result["outcome"]``.

    Drive it either inline (``run()`` blocks until takeover) or in the
    background (``start()``/``stop()``); the mutable standby state is
    written under ``_lock`` because the background thread and the public
    surface share it (analysis/locks.py enforces this structurally)."""

    def __init__(self, cfg, takeover: Callable[[int, Optional[Any]], Any],
                 metrics=None, registry=None,
                 monitor: Optional[HeartbeatMonitor] = None,
                 mailbox: Optional[WeightMailbox] = None,
                 lease_writer: Optional[HeartbeatWriter] = None,
                 injector: Optional[faults.FaultInjector] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.cfg = cfg
        self.takeover = takeover
        self.metrics = metrics
        self.registry = registry
        self.directory = heartbeat_dir(cfg)
        self.monitor = monitor if monitor is not None else HeartbeatMonitor(
            self.directory, cfg.heartbeat_timeout_s,
            self_id=getattr(cfg, "process_id", None),
            skew_tolerance_s=getattr(cfg, "lease_skew_tolerance_s", 0.0),
        )
        self.poll_s = float(getattr(cfg, "failover_poll_s", 0.5))
        self.warm = bool(getattr(cfg, "failover_warm", False))
        self._subscriber = (
            MailboxSubscriber(mailbox, consumer="standby")
            if self.warm and mailbox is not None else None
        )
        # the winner flips this lease (role -> learner, stamped with the new
        # epoch) the instant the claim is won, BEFORE the possibly
        # process-lifetime restore: sibling standbys judge "takeover in
        # progress" by it instead of waiting out the takeover deadline
        self.lease_writer = lease_writer
        self.takeover_deadline_s = float(
            getattr(cfg, "failover_takeover_deadline_s", 120.0))
        self.injector = injector if injector is not None else faults.get()
        self.clock = clock
        # the standby's own view of the highest learner epoch in play —
        # sourced from claim markers AND lease payloads, so it never claims
        # at or below an epoch it has already seen live
        self.fence = EpochFence()
        self.claims_lost = 0
        self.result: Optional[Dict[str, Any]] = None
        self._warm_params: Optional[Any] = None
        self._warm_version = -1
        self._death_t: Optional[float] = None
        self._holdoff_t0: Optional[float] = None  # takeover-in-progress wait
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------- emission
    def _row(self, event: str, **fields: Any) -> None:
        if self.metrics is not None:
            self.metrics.log("failover", event=event, **fields)

    # ------------------------------------------------------------ detection
    def _learner_leases(self) -> List[Lease]:
        self_id = getattr(self.cfg, "process_id", None)
        return [
            lease for lease in self.monitor.leases().values()
            if lease.role == LEARNER_ROLE and lease.host != self_id
        ]

    def _poll_warm(self) -> None:
        if self._subscriber is None:
            return
        params = self._subscriber.poll()
        if params is not None:
            with self._lock:
                self._warm_params = params
                self._warm_version = self._subscriber.version

    # ----------------------------------------------------------- claim race
    def _attempt_claim(self, now: float) -> Optional[Dict[str, Any]]:
        floor = max(
            latest_role_epoch(self.directory, LEARNER_ROLE),
            self.fence.epoch,
        )
        epoch = floor + 1
        with self._lock:
            death_t = self._death_t
        claim_s = None if death_t is None else round(now - death_t, 3)
        if self.injector.enabled and self.injector.fire("standby_claim"):
            # manufactured claim failure (a filesystem hiccup mid-O_EXCL):
            # reasoned row, re-arm — the next poll retries the race
            self._row("claim", won=False, epoch=epoch, claim_s=claim_s,
                      reason="injected_fault")
            return None
        won = claim_role_epoch(self.directory, LEARNER_ROLE, epoch)
        self.fence.observe(epoch)
        if not won:
            # a sibling standby won the filesystem race: it IS the learner
            # now — emit the reasoned loser row and go back to standby duty
            # watching the new incarnation's lease.  The hold-off clock
            # resets so the WINNER gets a full takeover deadline: the next
            # poll sees its claim marker above every lease and waits for
            # its learner-role lease instead of claiming epoch+1 unopposed
            # (two concurrent learners — the dual-takeover race).
            with self._lock:
                self.claims_lost += 1
                self._death_t = None
                self._holdoff_t0 = None
            self._row("claim", won=False, epoch=epoch, claim_s=claim_s,
                      reason="lost_race")
            return None
        self._row("claim", won=True, epoch=epoch, claim_s=claim_s)
        if self.lease_writer is not None:
            # Advertise the new incumbency IMMEDIATELY, before the (possibly
            # process-lifetime) restore: sibling standbys see a fresh
            # learner-role lease at this epoch through the whole recovery
            # instead of the deceased learner's stale one — without it they
            # can only hold off on the claim marker until the takeover
            # deadline.  A failed beat degrades to exactly that hold-off, so
            # it must not abort the takeover itself.
            try:
                self.lease_writer.update_payload(
                    role=LEARNER_ROLE, learner_epoch=epoch)
                self.lease_writer.beat()
            except OSError:
                pass
        # the takeover row lands when the role is WON, before the (possibly
        # process-lifetime — run_standby's callback IS the resumed train
        # loop) recovery work: RunHealth degrades the window at the right
        # moment and the restore row closes the latency split afterwards
        mttr_s = (None if death_t is None
                  else round(self.clock() - death_t, 3))
        self._row("takeover", epoch=epoch, mttr_s=mttr_s, warm=self.warm,
                  claim_s=claim_s)
        if self.registry is not None:
            self.registry.counter("failover_takeovers", "standby").inc()
            if mttr_s is not None:
                self.registry.gauge("failover_mttr_s", "standby").set(mttr_s)
        with self._lock:
            warm_params = self._warm_params
            warm_version = self._warm_version
        t_restore0 = self.clock()
        outcome = self.takeover(
            epoch, warm_params if self.warm else None)
        restore_s = round(self.clock() - t_restore0, 3)
        self._row("restore", epoch=epoch, restore_s=restore_s,
                  warm=self.warm, warm_version=warm_version)
        result = {"epoch": epoch, "mttr_s": mttr_s, "claim_s": claim_s,
                  "restore_s": restore_s, "warm": self.warm,
                  "outcome": outcome}
        with self._lock:
            self.result = result
        return result

    # ------------------------------------------------------------ main loop
    def poll(self) -> Optional[Dict[str, Any]]:
        """One standby sweep.  Returns the takeover result dict once this
        standby has taken the role over, None while on standby duty."""
        with self._lock:
            if self.result is not None:
                return self.result
        self._poll_warm()
        leases = self._learner_leases()
        for lease in leases:
            self.fence.observe(lease.learner_epoch)
        now = self.clock()
        if any(lease.fresh for lease in leases):
            with self._lock:
                self._death_t = None  # a live learner: nothing to do
                self._holdoff_t0 = None
            return None
        if not leases:
            return None  # no learner has EVER beaten; absence is not death
        claimed = latest_role_epoch(self.directory, LEARNER_ROLE)
        lease_peak = max(lease.learner_epoch for lease in leases)
        if claimed > lease_peak:
            # A claim marker ABOVE every learner-role lease ever written: a
            # sibling won the race and is mid-restore — its learner lease
            # only appears once its takeover beats (the lease_writer
            # advertisement, or the resumed train loop's own heartbeat).
            # Claiming now would be a SECOND, unopposed takeover — two
            # concurrent learners restoring into one run dir, exactly the
            # split brain the O_EXCL race exists to prevent — so hold off.
            # Only a claimant silent past the takeover deadline is presumed
            # dead mid-restore; then the claim race reopens above its epoch.
            with self._lock:
                first = self._holdoff_t0 is None
                if first:
                    self._holdoff_t0 = now
                held_s = now - self._holdoff_t0
            if first:
                self._row("holdoff", epoch=claimed, lease_epoch=lease_peak,
                          deadline_s=self.takeover_deadline_s)
            if held_s < self.takeover_deadline_s:
                return None
        with self._lock:
            if self._death_t is None:
                self._death_t = now
        return self._attempt_claim(now)

    def run(self, max_wait_s: Optional[float] = None
            ) -> Optional[Dict[str, Any]]:
        """Block on standby duty until takeover (returns its result),
        ``stop()``, or ``max_wait_s`` elapses (returns None)."""
        t0 = self.clock()
        while not self._stop.is_set():
            out = self.poll()
            if out is not None:
                return out
            if max_wait_s is not None and self.clock() - t0 >= max_wait_s:
                return None
            self._stop.wait(self.poll_s)
        return None

    def _run(self) -> None:
        self.run()

    def start(self) -> "StandbyLearner":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="standby-learner", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None


def mailbox_path(cfg) -> str:
    """The run's conventional WeightMailbox location — one path both the
    publisher (scripts/chaos_soak.py learners) and the warm standby's
    subscriber derive from cfg, so neither needs a side channel."""
    return os.path.join(cfg.results_dir, cfg.run_id, "mailbox.json")


def run_standby(cfg, max_wait_s: Optional[float] = None) -> Dict[str, Any]:
    """Process entry for a hot-standby learner (launch_apex.sh --standby,
    train_agent_apex.py --role standby).

    Tails the learner's lease in this run's heartbeat dir, writes its own
    ``standby`` lease when heartbeats are on (a process_id DISTINCT from
    the learner's is REQUIRED — see below), and on takeover re-enters the
    standard apex entry with ``resume="auto"`` as process 0: `train_apex`
    claims the NEXT learner-role epoch itself (strictly above both the
    deceased learner's and this standby's claim marker), restores the
    newest VALID checkpoint — scanning past a torn newest step — plus the
    CRC-verified replay snapshot, and resumes publishing strictly above
    the predecessor.  The standby's lease doubles as the takeover
    advertisement: the moment the claim is won it flips to role=learner at
    the new epoch, so sibling standbys hold off through the restore
    instead of racing a second takeover.  Warm mode additionally tails the
    run's mailbox so harnesses that inject their own takeover callback
    (scripts/chaos_soak.py) start from the freshest publish; the
    train_apex path restores from the checkpoint either way.

    Raises ValueError when ``process_id`` is left at the learner's id (0):
    that standby would write no lease of its own (invisible to
    HeartbeatMonitor and obs) AND filter the learner's lease out of its
    own death detection (the self-exclusion in ``_learner_leases``), so it
    could never take over — refusing loudly beats a silent no-op standby.

    Returns {"takeover": bool, ...} with the StandbyLearner result fields
    (epoch/mttr_s/claim_s/restore_s/outcome) when a takeover happened."""
    from rainbow_iqn_apex_tpu.utils.logging import MetricsLogger

    pid = int(getattr(cfg, "process_id", 0) or 0)
    if pid == 0:
        raise ValueError(
            "run_standby: process_id 0 is the learner's id — a standby "
            "sharing it writes no lease of its own (invisible to the "
            "HeartbeatMonitor and obs) and excludes the learner's lease "
            "from its own death detection, so it would never take over; "
            "launch with a distinct --process-id (launch_apex.sh "
            "--standby uses 1)")
    run_dir = os.path.join(cfg.results_dir, cfg.run_id)
    metrics = MetricsLogger(
        os.path.join(run_dir, "standby.jsonl"), cfg.run_id,
        echo=False, host=getattr(cfg, "process_id", 0),
    )
    faults.install_from(cfg)
    # live fleet telemetry (obs/net/): an idle standby is exactly the kind
    # of silent process a dashboard must see — attach a relay when the
    # plane is on (None otherwise; the standby stays jax-free either way)
    obs_relay = None
    if getattr(cfg, "obs_net", False):
        from rainbow_iqn_apex_tpu.obs.net.relay import ObsRelay

        obs_relay = ObsRelay.attach(cfg, metrics, role="standby")

    def takeover(epoch: int, warm_params: Optional[Any]) -> Any:
        # the jax-heavy half, imported only when the role is actually
        # claimed — the idle standby never pays the device-runtime tax
        import dataclasses

        from rainbow_iqn_apex_tpu.parallel.apex import train_apex

        return train_apex(
            dataclasses.replace(cfg, resume="auto", process_id=0))

    mailbox = (WeightMailbox(mailbox_path(cfg))
               if getattr(cfg, "failover_warm", False) else None)
    heartbeat = None
    if cfg.heartbeat_interval_s > 0:
        heartbeat = HeartbeatWriter(
            heartbeat_dir(cfg), pid, cfg.heartbeat_interval_s,
            role="standby",
        ).start()
    standby = StandbyLearner(cfg, takeover, metrics=metrics, mailbox=mailbox,
                             lease_writer=heartbeat)
    try:
        result = standby.run(max_wait_s=max_wait_s)
    finally:
        if heartbeat is not None:
            heartbeat.stop()
        if obs_relay is not None:
            obs_relay.close()
        metrics.close()
    if result is None:
        return {"takeover": False, "claims_lost": standby.claims_lost}
    out = dict(result)
    out["takeover"] = True
    return out
