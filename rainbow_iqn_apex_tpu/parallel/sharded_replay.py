"""Sharded prioritized replay — the Redis-shard topology in host DRAM.

Parity: reference component row 6 (SURVEY.md §2): replay contents sharded
across multiple redis-server instances so many actors append and one learner
samples, with remote priority write-back.  Here each shard is a
PrioritizedReplay owned by the host (one per pod host in the multi-host
picture; several in-process shards model the same topology single-host), and
"remote" traffic becomes NumPy writes — the learner's sample mixes
sub-batches drawn from every shard in proportion to total shard priority
mass, which is exactly proportional global sampling (the same distribution a
single giant tree would give).
"""

from __future__ import annotations

import time
from typing import List, Optional, Sequence, Tuple

import numpy as np

from rainbow_iqn_apex_tpu.replay.buffer import PrioritizedReplay, SampledBatch
from rainbow_iqn_apex_tpu.utils import faults, hostsync


class ShardedReplay:
    """K independent PER shards behind the single-buffer interface.

    Lane -> shard assignment is static (contiguous blocks), mirroring the
    reference's actor->redis-shard pinning; global slot ids are
    (shard_id * shard_capacity + local_slot).
    """

    def __init__(self, shards: Sequence[PrioritizedReplay]):
        if not shards:
            raise ValueError("need at least one shard")
        caps = {s.capacity for s in shards}
        if len(caps) != 1:
            raise ValueError("all shards must share a capacity")
        self.shards: List[PrioritizedReplay] = list(shards)
        self.shard_capacity = shards[0].capacity
        self.lanes_per_shard = shards[0].lanes
        self.rng = np.random.default_rng(shards[0].rng.integers(2**31))
        # graceful degradation: shards marked dead (their host stopped
        # heartbeating / their backing store is gone) are excluded from
        # append/sample/write-back so the learner keeps training on the
        # survivors instead of wedging (docs/RESILIENCE.md)
        self._dead: set = set()
        # elasticity (docs/RESILIENCE.md "heal"): each shard carries the
        # lease epoch of the incarnation allowed to write it.  drop ->
        # readmit bumps the epoch, so a zombie pre-eviction incarnation's
        # appends/write-backs are fenced off instead of corrupting the
        # readmitted shard (split-brain protection).
        self._epoch: List[int] = [0] * len(self.shards)
        self._fenced_writes = 0
        self._reg = None  # obs registry (attach_registry); None = untracked
        self._frontier = None  # device sample frontier (attach_frontier)
        # pipeline tracing (obs/pipeline_trace.py): every written slot is
        # stamped with the append tick + wall clock it landed on, so sample
        # time can attribute each batch's AGE (ticks + seconds) and derive
        # the env-tick trace ids the learn span links back to.  16 bytes per
        # slot, two scatter writes per append tick — always-on cheap; no
        # numerics touched, so the untraced path stays bitwise identical.
        n_slots = len(self.shards) * self.shard_capacity
        self._append_seq = np.zeros(n_slots, np.int64)
        self._append_ts = np.zeros(n_slots, np.float64)
        self.append_ticks = 0  # monotone appends-per-lane counter
        self._tracer = None

    def attach_registry(self, registry, role: str = "replay") -> None:
        """obs/ wiring: appended/sampled row counters + occupancy and
        dead-shard gauges under the given role label."""
        self._reg = registry
        self._role = role
        registry.gauge("replay_shards", role).set(len(self.shards))

    def attach_tracer(self, tracer) -> None:
        """Pipeline-tracing wiring (obs/pipeline_trace.py): sample/assemble
        record batch sample-age lags; ``trace_ids`` maps sampled slots back
        to the append ticks that wrote them (the learn span's flow links)."""
        self._tracer = tracer

    def _stamp_append(self, k: int, shard: PrioritizedReplay,
                      pos_before: int) -> None:
        slots = k * self.shard_capacity + shard._lane_base + pos_before
        self._append_seq[slots] = self.append_ticks
        self._append_ts[slots] = time.time()

    def _record_sample_age(self, idx: np.ndarray) -> None:
        if self._tracer is None or idx.size == 0:
            return
        ts = self._append_ts[idx]
        written = ts > 0  # pre-attach / restored slots carry no stamp
        if not written.any():
            return
        self._tracer.lag("sample_age_ticks", float(
            (self.append_ticks - self._append_seq[idx][written]).mean()))
        self._tracer.lag("sample_age_s",
                         float((time.time() - ts[written]).mean()))

    def trace_ids(self, idx: np.ndarray) -> np.ndarray:
        """Append tick of each global slot in ``idx`` (0 = never stamped)."""
        return self._append_seq[np.asarray(idx, np.int64)]

    def attach_frontier(self, frontier) -> None:
        """Device-sampling wiring (replay/frontier.py): subsequent appends
        stage their tree leaf deltas to the HBM priority mirror, and shard
        drop/readmit fence the mirror alongside the host epoch."""
        self._frontier = frontier

    def _stage_frontier_delta(self, k: int, shard: PrioritizedReplay,
                              pos_before: int) -> None:
        """Mirror one append tick's three disjoint leaf updates (fresh slot,
        cursor dead zone, ready slot — see buffer._append_locked) by reading
        the freshly written tree values back: works identically for the
        NumPy and native-core append paths, and re-staging an unchanged
        ready value is harmless."""
        seg = shard.seg
        new_pos = (pos_before + 1) % seg
        cols = np.concatenate([
            np.asarray(
                [pos_before, (pos_before - shard.n_step) % seg], np.int64
            ),
            (new_pos + np.arange(shard.history, dtype=np.int64)) % seg,
        ])
        slots = (shard._lane_base[:, None] + cols[None, :]).ravel()
        self._frontier.stage(
            k * self.shard_capacity + slots, shard.tree.get(slots)
        )

    def _observe(self) -> None:
        if self._reg is None:
            return
        cap = self.shard_capacity * (len(self.shards) - len(self._dead))
        self._reg.gauge("replay_size", self._role).set(len(self))
        self._reg.gauge("replay_occupancy", self._role).set(
            len(self) / max(cap, 1)
        )
        self._reg.gauge("replay_dead_shards", self._role).set(len(self._dead))

    @classmethod
    def build(
        cls, num_shards: int, capacity_total: int, lanes_total: int, **kwargs
    ) -> "ShardedReplay":
        if capacity_total % num_shards or lanes_total % num_shards:
            raise ValueError("capacity and lanes must divide evenly into shards")
        seed = kwargs.pop("seed", 0)
        shards = [
            PrioritizedReplay(
                capacity_total // num_shards,
                lanes=lanes_total // num_shards,
                seed=seed + 1000 * k,
                **kwargs,
            )
            for k in range(num_shards)
        ]
        return cls(shards)

    # ------------------------------------------------------------------ append
    def append_batch(
        self,
        frames: np.ndarray,
        actions: np.ndarray,
        rewards: np.ndarray,
        terminals: np.ndarray,
        priorities: Optional[np.ndarray] = None,
        truncations: Optional[np.ndarray] = None,
    ) -> None:
        """Lockstep append of all lanes, block-partitioned across shards.
        Lanes pinned to a dead shard are dropped (their actor host is gone;
        the surviving shards keep absorbing their own lanes)."""
        lps = self.lanes_per_shard
        self.append_ticks += 1
        for k, shard in enumerate(self.shards):
            if k in self._dead:
                continue
            sl = slice(k * lps, (k + 1) * lps)
            pos_before = shard.pos
            shard.append_batch(
                frames[sl],
                actions[sl],
                rewards[sl],
                terminals[sl],
                None if priorities is None else priorities[sl],
                None if truncations is None else truncations[sl],
            )
            self._stamp_append(k, shard, pos_before)
            if self._frontier is not None:
                self._stage_frontier_delta(k, shard, pos_before)
            if self._reg is not None:
                self._reg.counter("replay_appended_rows", self._role).inc(lps)
        self._observe()

    def __len__(self) -> int:
        return sum(len(s) for k, s in enumerate(self.shards) if k not in self._dead)

    @property
    def sampleable(self) -> bool:
        """ANY alive shard with priority mass makes the aggregate
        sampleable: ``sample`` already hands a zero-mass shard a zero
        multinomial count, and requiring ALL alive shards to hold data
        would let one cold readmitted shard (an explicitly supported
        healing state) halt a learner whose surviving shards are full."""
        return any(
            s.sampleable
            for k, s in enumerate(self.shards) if k not in self._dead
        )

    # -------------------------------------------------------------- degradation
    def drop_shard(self, k: int) -> None:
        """Mark shard ``k`` dead: its lanes stop appending, its contents stop
        being sampled, priority write-backs to it are dropped.  Idempotent.
        The learner's sample distribution renormalises over the survivors —
        exactly what losing one redis-server of a sharded fleet means."""
        if not 0 <= k < len(self.shards):
            raise ValueError(f"no shard {k} (have {len(self.shards)})")
        if len(self._dead) >= len(self.shards) - 1 and k not in self._dead:
            raise RuntimeError("cannot drop the last surviving replay shard")
        already = k in self._dead
        self._dead.add(k)
        if self._frontier is not None and not already:
            # fence the HBM mirror too: zero the slice so device draws
            # renormalise over survivors exactly like the host sample
            self._frontier.on_drop(k)
        self._observe()

    @property
    def dead_shards(self) -> Tuple[int, ...]:
        return tuple(sorted(self._dead))

    # -------------------------------------------------------------- elasticity
    def shard_epoch(self, k: int) -> int:
        """The lease epoch currently allowed to write shard ``k``."""
        return self._epoch[k]

    @property
    def fenced_writes(self) -> int:
        """Appends/write-backs rejected by epoch fencing (lifetime)."""
        return self._fenced_writes

    def readmit_shard(self, k: int, epoch: Optional[int] = None,
                      reseed_priority: bool = True) -> int:
        """Reverse ``drop_shard``: a rejoining host re-registers its (empty
        or snapshot-restored) shard under a NEW lease epoch.  Sampling
        rebalances over the survivor set automatically (the proportional
        split sees the shard's mass again), and the shard's default append
        priority is re-seeded from the survivors' current max so a cold
        rejoining shard's fresh experience competes immediately instead of
        starving behind a year of accumulated priority mass.  Returns the
        epoch that now owns the shard; the ``shard_rejoin`` fault point
        makes the re-registration itself fail once (callers retry under the
        shared RetryPolicy)."""
        if not 0 <= k < len(self.shards):
            raise ValueError(f"no shard {k} (have {len(self.shards)})")
        if k not in self._dead:
            raise ValueError(f"shard {k} is not dead; nothing to readmit")
        injector = faults.get()
        if injector.enabled and injector.fire("shard_rejoin"):
            raise OSError(f"injected shard_rejoin failure for shard {k}")
        new_epoch = self._epoch[k] + 1 if epoch is None else int(epoch)
        # equal epoch is legal: a false-positive drop (lease blip) readmits
        # the SAME incarnation, whose writes stay valid; only an OLDER epoch
        # — a superseded incarnation — is an error
        if new_epoch < self._epoch[k]:
            raise ValueError(
                f"readmission epoch {new_epoch} is older than the fenced "
                f"epoch {self._epoch[k]} for shard {k}"
            )
        if reseed_priority:
            survivor_max = [
                s.max_priority for j, s in enumerate(self.shards)
                if j != k and j not in self._dead
            ]
            if survivor_max:
                self.shards[k].max_priority = max(
                    max(survivor_max), self.shards[k].max_priority
                )
        self._dead.discard(k)
        self._epoch[k] = new_epoch
        if self._frontier is not None:
            # the mirror re-reads the readmitted shard's host tree (the cold
            # source of truth the rejoining host restored) under a fresh
            # frontier epoch, so sample-ahead batches drawn pre-readmission
            # are countable as stale
            self._frontier.on_readmit(k)
        if self._reg is not None:
            self._reg.counter("replay_shard_readmits", self._role).inc()
        self._observe()
        return new_epoch

    def _fence(self, k: int, epoch: Optional[int]) -> bool:
        """True when a write stamped ``epoch`` may land on shard ``k``."""
        if k in self._dead:
            return False
        if epoch is not None and int(epoch) != self._epoch[k]:
            self._fenced_writes += 1
            if self._reg is not None:
                self._reg.counter("replay_fenced_writes", self._role).inc()
            return False
        return True

    def append_shard(
        self,
        k: int,
        frames: np.ndarray,
        actions: np.ndarray,
        rewards: np.ndarray,
        terminals: np.ndarray,
        priorities: Optional[np.ndarray] = None,
        truncations: Optional[np.ndarray] = None,
        epoch: Optional[int] = None,
    ) -> bool:
        """Epoch-fenced single-shard append (the elastic ingest path: one
        producer host feeds exactly its own shard).  Returns False — and
        drops the rows — when the shard is dead or ``epoch`` names a stale
        incarnation; True when the rows landed."""
        if not 0 <= k < len(self.shards):
            raise ValueError(f"no shard {k} (have {len(self.shards)})")
        if not self._fence(k, epoch):
            return False
        pos_before = self.shards[k].pos
        self.append_ticks += 1
        self.shards[k].append_batch(
            frames, actions, rewards, terminals, priorities, truncations
        )
        self._stamp_append(k, self.shards[k], pos_before)
        if self._frontier is not None:
            self._stage_frontier_delta(k, self.shards[k], pos_before)
        if self._reg is not None:
            self._reg.counter("replay_appended_rows", self._role).inc(
                len(actions)
            )
        self._observe()
        return True

    def update_shard_priorities(
        self, k: int, local_idx: np.ndarray, td_abs: np.ndarray,
        epoch: Optional[int] = None,
    ) -> bool:
        """Epoch-fenced per-shard priority write-back (same fence as
        ``append_shard``; a stale incarnation's TD estimates must not skew
        the readmitted shard's sampling distribution)."""
        if not 0 <= k < len(self.shards):
            raise ValueError(f"no shard {k} (have {len(self.shards)})")
        if not self._fence(k, epoch):
            return False
        self.shards[k].update_priorities(local_idx, td_abs)
        return True

    # ------------------------------------------------------------------ sample
    def sample(self, batch_size: int, beta: float) -> SampledBatch:
        """Proportional global sample: shard k contributes ~ its share of the
        total priority mass (multinomial split), then samples locally."""
        hostsync.check_host_work("replay_sample")
        totals = np.asarray(
            [
                0.0 if k in self._dead else s.tree.total
                for k, s in enumerate(self.shards)
            ],
            np.float64,
        )
        if totals.sum() <= 0:
            raise ValueError("cannot sample: all surviving shards empty")
        counts = self.rng.multinomial(batch_size, totals / totals.sum())
        # a zero-count shard simply doesn't contribute this batch (matches
        # multi-redis sampling); the multinomial split makes the overall draw
        # exactly proportional to global priority mass.
        parts: List[SampledBatch] = []
        probs: List[np.ndarray] = []
        n_global = len(self)
        for k, (shard, c) in enumerate(zip(self.shards, counts)):
            if c == 0:
                continue
            b = shard.sample(int(c), beta)
            parts.append(
                SampledBatch(
                    idx=b.idx + k * self.shard_capacity,
                    obs=b.obs,
                    action=b.action,
                    reward=b.reward,
                    next_obs=b.next_obs,
                    discount=b.discount,
                    weight=b.weight,  # replaced below with the global version
                    prob=b.prob,
                )
            )
            # global sample probability: local prob scaled by the shard's
            # share of total priority mass
            probs.append(b.prob * (totals[k] / totals.sum()))

        if self._reg is not None:
            self._reg.counter("replay_sampled_rows", self._role).inc(batch_size)
        cat = lambda f: np.concatenate([getattr(p, f) for p in parts])  # noqa: E731
        prob = np.concatenate(probs)
        idx_all = cat("idx")
        self._record_sample_age(idx_all)
        weight = (n_global * np.maximum(prob, 1e-12)) ** (-beta)
        weight = (weight / weight.max()).astype(np.float32)
        return SampledBatch(
            idx=idx_all,
            obs=cat("obs"),
            action=cat("action"),
            reward=cat("reward"),
            next_obs=cat("next_obs"),
            discount=cat("discount"),
            weight=weight,
            prob=prob,
        )

    def eligible_mask(self, idx: np.ndarray) -> np.ndarray:
        """True where global slot ``idx`` is CURRENTLY eligible (host-tree
        leaf > 0 on an alive shard).  The append path maintains the
        invariant that every slot whose history/n-step window would cross
        the write cursor carries zero priority, so a sample-ahead batch can
        re-check its device-drawn indices at GATHER time: rows invalidated
        by cursor movement since the draw read as False (their assembly
        would mix frames from two ring laps) and get their IS weight zeroed
        instead of training on straddled transitions."""
        idx = np.asarray(idx, np.int64).ravel()
        shard_of = idx // self.shard_capacity
        local = idx % self.shard_capacity
        ok = np.zeros(idx.shape[0], bool)
        in_range = (idx >= 0) & (idx < len(self.shards) * self.shard_capacity)
        for k, shard in enumerate(self.shards):
            if k in self._dead:
                continue
            m = (shard_of == k) & in_range
            if m.any():
                ok[m] = shard.tree.get(local[m]) > 0
        return ok

    def assemble_global(
        self,
        idx: np.ndarray,
        weight: np.ndarray,
        prob: Optional[np.ndarray] = None,
    ) -> SampledBatch:
        """Index-driven batch assembly at already-drawn global slot ids (the
        device-sampling hot path: the frontier drew ``idx`` and computed
        ``weight`` in HBM; the host's remaining job is this frame gather).

        Rows come back sorted by slot id.  PER batches are exchangeable —
        per-row weights/probs travel with their rows — and the frontier's
        stratified draw emits slot-sorted indices already, so sorting is
        usually a no-op; it makes every shard's rows one CONTIGUOUS slice
        of the output, which the native core fills IN PLACE (zero extra
        copies — the host sample path's per-shard concatenate pays one
        full batch copy here)."""
        idx = np.asarray(idx, np.int64).ravel()
        weight = np.asarray(weight, np.float32).ravel()
        B = idx.shape[0]
        n_slots = len(self.shards) * self.shard_capacity
        if B and (idx.min() < 0 or idx.max() >= n_slots):
            # match PrioritizedReplay.assemble: silent np.empty rows for
            # out-of-range ids would train on garbage
            raise IndexError(f"assemble_global idx out of range [0, {n_slots})")
        if np.any(idx[1:] < idx[:-1]):  # host callers may pass unsorted
            order = np.argsort(idx, kind="stable")
            idx, weight = idx[order], weight[order]
            if prob is not None:
                prob = np.asarray(prob).ravel()[order]
        shard_of = idx // self.shard_capacity
        local = idx % self.shard_capacity
        s0 = self.shards[0]
        h, w = s0.frames.shape[1], s0.frames.shape[2]
        obs = np.empty((B, h, w, s0.history), np.uint8)
        next_obs = np.empty_like(obs)
        action = np.empty(B, np.int32)
        reward = np.empty(B, np.float32)
        discount = np.empty(B, np.float32)
        bounds = np.searchsorted(shard_of, np.arange(len(self.shards) + 1))
        for k, shard in enumerate(self.shards):
            lo, hi = int(bounds[k]), int(bounds[k + 1])
            if lo == hi:
                continue
            sl = slice(lo, hi)
            shard.assemble(local[sl], out=(
                obs[sl], next_obs[sl], action[sl], reward[sl], discount[sl],
            ))
        if self._reg is not None:
            self._reg.counter("replay_sampled_rows", self._role).inc(B)
        self._record_sample_age(idx)
        return SampledBatch(
            idx=idx,
            obs=obs,
            action=action,
            reward=reward,
            next_obs=next_obs,
            discount=discount,
            weight=weight,
            prob=None if prob is None else np.asarray(prob).ravel(),
        )

    # -------------------------------------------------------------- snapshot
    def snapshot(self, path_prefix: str) -> None:
        """One npz per shard (the per-host persistence unit in the pod
        picture, mirroring per-redis-instance RDB files) plus a tiny meta
        file carrying the shard-split RNG, so a resumed learner draws the
        same shard mix the uninterrupted run would have."""
        import json

        from rainbow_iqn_apex_tpu.replay import snapshot_io

        for k, shard in enumerate(self.shards):
            shard.snapshot(f"{path_prefix}_shard{k}")
        snapshot_io.atomic_savez(
            f"{path_prefix}_meta",
            rng_state=np.frombuffer(
                json.dumps(self.rng.bit_generator.state).encode(), np.uint8
            ),
            # elasticity state: writer epochs + dead set, so a resumed run
            # keeps fencing the same stale incarnations it fenced before
            shard_epochs=np.asarray(self._epoch, np.int64),
            dead_shards=np.asarray(sorted(self._dead), np.int64),
        )

    def restore(self, path_prefix: str) -> None:
        import json
        import os

        from rainbow_iqn_apex_tpu.replay import snapshot_io

        # check the whole shard set up front — existence AND CRC — so a kill
        # that landed between shard writes, or one torn shard file, reads as
        # "no snapshot" instead of a half-restored mix.  The verified
        # payloads are applied directly (one disk read per shard, not two).
        paths = [f"{path_prefix}_shard{k}" for k in range(len(self.shards))]
        for p in paths:
            if not os.path.exists(snapshot_io.npz_path(p)):
                raise FileNotFoundError(snapshot_io.npz_path(p))
        payloads = [snapshot_io.load(p) for p in paths]  # SnapshotCorrupt here
        for shard, z in zip(self.shards, payloads):
            shard.apply_snapshot(z)
        try:  # pre-resilience snapshots carry no meta file
            meta = snapshot_io.load(f"{path_prefix}_meta")
            self.rng.bit_generator.state = json.loads(
                np.asarray(meta["rng_state"], np.uint8).tobytes().decode()
            )
            if "shard_epochs" in meta:  # pre-elastic metas carry neither
                epochs = np.asarray(meta["shard_epochs"], np.int64)
                if len(epochs) == len(self.shards):
                    self._epoch = [int(e) for e in epochs]
                self._dead = {int(k) for k in np.asarray(
                    meta["dead_shards"], np.int64)}
        except snapshot_io.MISSING:
            pass
        if self._frontier is not None:
            self._frontier.refresh_from_host(dead=self._dead)

    # ------------------------------------------------------------- live retune
    @property
    def max_n_step(self) -> int:
        """Largest n every shard's geometry admits (league genome clamp)."""
        return min(s.max_n_step for s in self.shards)

    def set_n_step(self, n_step: int) -> None:
        """Mid-run n-step adoption (league/ live gene): every shard
        re-fences its eligibility under the new window.  Callers adopt at a
        drain boundary with the device frontier OFF — the HBM mirror stages
        deltas under the old window geometry (league member loops fall back
        to host sampling, parallel/apex.py)."""
        for shard in self.shards:
            shard.set_n_step(n_step)

    def set_priority_exponent(self, omega: float) -> None:
        """Mid-run omega adoption (league/ live gene): future write-backs
        use the new exponent on every shard."""
        for shard in self.shards:
            shard.set_priority_exponent(omega)

    # -------------------------------------------------------------- priorities
    def update_priorities(self, idx: np.ndarray, td_abs: np.ndarray) -> None:
        shard_of = idx // self.shard_capacity
        local = idx % self.shard_capacity
        for k, shard in enumerate(self.shards):
            if k in self._dead:
                continue  # write-backs racing a shard death are dropped
            m = shard_of == k
            if m.any():
                shard.update_priorities(local[m], td_abs[m])
