"""Device mesh construction and learner/actor partitioning.

The reference couples 1 learner process + N actor processes through Redis TCP
(SURVEY.md §1).  The TPU-native replacement (north star BASELINE.json:5) makes
one SPMD program own the whole slice — every dispatch in the tree already
goes through the modern ``jax.jit`` + ``NamedSharding`` path (there is no
pmap anywhere; in/out shardings on named meshes, XLA inserts the
collectives):

- a **learner mesh** with axis ``dp``: the learn step runs batch-sharded over
  it (params replicated, XLA inserts the gradient all-reduce over ICI);
- an **actor mesh** with axis ``actor``: batched vector-env inference is
  sharded lane-wise across it;
- weight publish = one device_put of (optionally bf16, or int8-quantized —
  utils/quantize.py) params from the learner mesh to the actor mesh — the
  Redis weight-mailbox replaced by an ICI broadcast.

On a single chip both meshes are the same device and the roles time-multiplex;
on a pod ``Config.learner_devices`` carves the slice.

Remaining mesh work (ROADMAP "Mesh generality"): both meshes are still 1-D —
growing them into a logical 2-D ``(batch, model)`` mesh (a ``model`` axis for
head/embedding sharding, `shard_map` where XLA's sharding inference falls
short) and running the queued batch-512/1024 scaling sweep are the open
items; the jit/NamedSharding migration itself is long done.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def parse_mesh_shape(spec: str) -> List[Tuple[str, int]]:
    """Parse "dp=4,actor=4" into [("dp", 4), ("actor", 4)]."""
    if not spec:
        return []
    out = []
    for part in spec.split(","):
        name, _, num = part.partition("=")
        out.append((name.strip(), int(num)))
    return out


def split_devices(
    devices: Optional[Sequence[jax.Device]] = None, learner_devices: int = 0
) -> Tuple[List[jax.Device], List[jax.Device]]:
    """Carve the device list into (learner, actor) sets.

    learner_devices == 0 means no split: every device plays both roles
    (single-chip and small-slice mode — roles time-multiplex like the
    reference's 1-GPU learner+actor colocated runs).
    """
    devices = list(devices if devices is not None else jax.devices())
    if learner_devices <= 0 or learner_devices >= len(devices):
        return devices, devices
    return devices[:learner_devices], devices[learner_devices:]


def learner_mesh(devices: Sequence[jax.Device]) -> Mesh:
    return Mesh(np.asarray(devices), axis_names=("dp",))


def actor_mesh(devices: Sequence[jax.Device]) -> Mesh:
    return Mesh(np.asarray(devices), axis_names=("actor",))


def batch_sharding(mesh: Mesh, axis: str = "dp") -> NamedSharding:
    """Leading-axis sharding for batches: [B, ...] split across the mesh."""
    return NamedSharding(mesh, P(axis))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
