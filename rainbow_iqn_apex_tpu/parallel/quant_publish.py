"""Shared gated quantized-publish surface for the two apex drivers.

`ApexDriver` and `R2D2ApexDriver` must not drift on the publish surface
(version stamps, the agreement gate, the fallback semantics, the
`publish`/`quant`/`quant_fallback` rows) — so the surface lives ONCE here
instead of being copy-pasted into both.  The mixin owns everything that is
architecture-independent: mode/config state, the calibration handshake with
the loop, row/gauge emission, byte accounting, and the gated
`publish_weights` itself.  Each driver supplies only the pieces its act
signature shapes:

- ``_gate_actions(params, qparams)`` — run the fp32 and quantized policies
  on the held calibration batch under the SAME key (same taus/noise) and
  return the two greedy-action device arrays;
- ``set_calibration(obs_batch)`` — stage the replay-drawn calibration
  observations (the r2d2 override also builds the zero LSTM state the gate
  compares under);
- ``self._rep_a`` — the actor-mesh replicated sharding the publish targets;
- lane-sharded quantized act twins (``_act_q``/``_stack_act_q``) built
  against the mode `_init_quant_publish` returns.

Single-host only: an SPMD pod must not diverge on a per-host gate decision,
so `_init_quant_publish(multihost=True)` declines with
``quant_disabled_reason = "multihost"`` and the loop logs the notice (the
cfg is identical on every host, so the whole pod declines together).
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np

from rainbow_iqn_apex_tpu.utils import hostsync
from rainbow_iqn_apex_tpu.utils.quantize import (
    check_mode,
    greedy_agreement,
    quantize_for_mode,
)


class QuantPublishMixin:
    """Gated int8/fp8 weight publish with fp32/bf16 fallback (PR 8)."""

    # ------------------------------------------------------------- lifecycle
    def _init_quant_publish(self, cfg, multihost: bool) -> str:
        """Install the common quant-publish state; returns the EFFECTIVE
        mode ("off" when disabled) so the driver knows whether to build its
        quantized act twins."""
        self.quant_mode = "off"
        self.quant_disabled_reason: Optional[str] = None
        self._actor_quant = False
        self.quant_agreement: Optional[float] = None
        self.quant_fallbacks = 0
        self._calib_obs = None
        self._obs_metrics = None
        self._obs_registry = None
        self._obs_tracer = None
        # learner-failover fence state (parallel/failover.py): with no fence
        # attached (every pre-failover run) publish_weights is bitwise the
        # pre-failover path — the fence check short-circuits on None.
        self._epoch_fence = None
        self.learner_epoch = 0
        self.fenced_publishes = 0
        mode = check_mode(cfg.serve_quantize)
        if mode != "off" and multihost:
            self.quant_disabled_reason = "multihost"
            return "off"
        if mode != "off":
            self.quant_mode = mode
            self._quantize_pub = jax.jit(
                lambda p, m=mode: quantize_for_mode(p, m))
            self._gate_key = jax.random.PRNGKey(cfg.seed + 8221)
        return self.quant_mode

    def attach_obs(self, metrics=None, registry=None, tracer=None) -> None:
        """Hand the driver the run's metrics surface (the loop constructs
        the driver before the logger exists) so publishes can emit
        `publish`/`quant`/`quant_fallback` rows and gauges.  ``tracer`` (a
        PipelineTracer) additionally anchors publish->adopt lag attribution
        and, when span sampling is on, emits one `publish` span per
        broadcast under the weight version's trace id."""
        self._obs_metrics = metrics
        self._obs_registry = registry
        self._obs_tracer = tracer

    def attach_epoch_fence(self, fence, learner_epoch: int) -> None:
        """Arm the zombie-learner publish fence (parallel/failover.py): this
        driver publishes AS ``learner_epoch``; when the shared `EpochFence`
        has latched a higher epoch (a standby took the role over while this
        learner was paused, not dead), `publish_weights` refuses instead of
        broadcasting — the driver-side half of the two-layer fence whose
        authoritative cross-process half is the `WeightMailbox` disk row."""
        self._epoch_fence = fence
        self.learner_epoch = int(learner_epoch)

    def wants_calibration(self) -> bool:
        return self.quant_mode != "off" and self._calib_obs is None

    # ------------------------------------------------------------- emission
    def _quant_row(self, kind: str, **fields) -> None:
        if self._obs_metrics is not None:
            self._obs_metrics.log(kind, **fields)
        if self._obs_registry is not None:
            if kind == "quant_fallback":
                self._obs_registry.counter(
                    "quant_fallback_total", "learner").inc()
            if fields.get("agreement") is not None:
                self._obs_registry.gauge(
                    "quant_action_agreement", "learner").set(
                    float(fields["agreement"]))

    def _tree_wire_bytes(self, tree) -> int:
        """Logical bytes a publish of ``tree`` ships over ICI/DCN — static
        shape/dtype metadata only, no device sync."""
        return int(sum(x.size * x.dtype.itemsize
                       for x in jax.tree.leaves(tree)))

    # ----------------------------------------------------------------- gate
    def _gate_actions(self, params, qparams):
        """Driver hook: (fp32 actions, quantized actions) on the held
        calibration batch, same key for both policies."""
        raise NotImplementedError

    def _gate_agreement(self, params, qparams) -> float:
        a32, aq = self._gate_actions(params, qparams)
        with hostsync.sanctioned():  # publish boundary, ring already drained
            return greedy_agreement(np.asarray(a32), np.asarray(aq))

    # -------------------------------------------------------------- publish
    def publish_weights(self) -> int:
        """Learner -> actor-mesh broadcast (the Redis SET + actor GET pair).
        Returns the new monotonically increasing weight version; the actor
        mesh adopts it atomically with the params.

        With ``cfg.serve_quantize`` on (and a calibration batch set), the
        broadcast ships the int8/fp8 tree instead — gated per publish by
        greedy-action agreement against the fp32 policy; a failed gate
        falls back to today's fp32/bf16 broadcast and emits one reasoned
        ``quant_fallback`` row.  ``serve_quantize="off"`` takes exactly the
        pre-quant path."""
        import time as _time

        if (self._epoch_fence is not None
                and self._epoch_fence.stale(self.learner_epoch)):
            # zombie fence: a successor claimed the learner role at a higher
            # epoch while this learner was paused — refusing here keeps the
            # stale tree off the actor mesh entirely (the mailbox's disk-row
            # fence would also refuse, but only for out-of-process readers).
            self.fenced_publishes += 1
            if self._obs_metrics is not None:
                self._obs_metrics.log(
                    "failover", event="fenced_stale", surface="publish",
                    epoch=self.learner_epoch,
                    fence_epoch=self._epoch_fence.epoch,
                    version=self.weights_version,
                )
            return self.weights_version

        t_pub0 = _time.time()
        p = self.state.params
        published_mode = None
        if self.quant_mode != "off" and self._calib_obs is not None:
            qp = self._quantize_pub(p)  # int8/fp8 on the learner mesh
            agreement = self._gate_agreement(p, qp)
            self.quant_agreement = agreement
            if agreement >= self.cfg.quant_agreement_min:
                # only the quantized tree ever crosses to the actor mesh —
                # a gated publish never pays a second fp32 broadcast
                self.actor_params = jax.device_put(qp, self._rep_a)
                self._actor_quant = True
                published_mode = self.quant_mode
                published_bytes = self._tree_wire_bytes(qp)
                self._quant_row(
                    "quant", event="gate", mode=self.quant_mode, active=True,
                    agreement=round(agreement, 6),
                    threshold=self.cfg.quant_agreement_min,
                )
            else:
                self.quant_fallbacks += 1
                self._quant_row(
                    "quant_fallback", reason="agreement_below_min",
                    mode=self.quant_mode, agreement=round(agreement, 6),
                    threshold=self.cfg.quant_agreement_min,
                    step=self._host_step or 0,
                )
        if published_mode is None:
            if self.cfg.bf16_weight_sync:
                p = self._uncast(jax.device_put(self._cast(p), self._rep_a))
                published_mode = "bf16"
            else:
                p = jax.device_put(p, self._rep_a)
                published_mode = "fp32"
            self.actor_params = p
            self._actor_quant = False
            published_bytes = self._tree_wire_bytes(self.state.params) // (
                2 if published_mode == "bf16" else 1)
        self.weights_version += 1
        self.actor_weights_version = self.weights_version
        if self._obs_tracer is not None:
            # publish->adopt attribution: the fused driver adopts atomically
            # with the publish, so its in-process consumer measures the
            # broadcast itself; mailbox/fleet consumers anchor on the same
            # version.  One `publish` span per broadcast when sampling is on
            # (publishes are rare — every one is worth a span).
            tr = self._obs_tracer
            tr.note_publish(self.weights_version, ts=t_pub0)
            # sampled like every other stage: emitting a span per publish
            # while learn steps emit 1-in-N would overweight the publish
            # stage in critical_path by ~sample_every x
            if tr.sampled(self.weights_version):
                tr.emit_span(
                    "publish", tr.trace_id("w", self.weights_version), t_pub0,
                    version=self.weights_version, mode=published_mode,
                )
            tr.note_adopt("actor_inproc", self.weights_version)
        if self._obs_metrics is not None:
            self._obs_metrics.log(
                "publish", version=self.weights_version,
                bytes=published_bytes,
                bytes_fp32=self._tree_wire_bytes(self.state.params),
                mode=published_mode, quant_active=self._actor_quant,
            )
        if self._obs_registry is not None:
            self._obs_registry.counter(
                "publish_bytes_total", "learner").inc(published_bytes)
        return self.weights_version
