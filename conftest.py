"""Repo-root pytest bootstrap.

The sandbox's sitecustomize registers a remote TPU ("axon") PJRT plugin in
every interpreter whenever ``PALLAS_AXON_POOL_IPS`` is set; once registered,
completing ``import jax`` blocks on the TPU tunnel even under
``JAX_PLATFORMS=cpu``.  The test suite must run on a virtual 8-device CPU
platform (build contract), so before anything imports jax we re-exec the
interpreter with the axon trigger stripped and the CPU platform forced.
bench.py / training entry points are unaffected — they keep the real TPU env.

The re-exec happens in ``pytest_configure`` (not at conftest import) so we can
first stop pytest's fd-level output capture — otherwise the child's output
would vanish into the orphaned capture tempfiles.
"""

import os
import sys


def pytest_configure(config):
    if not os.environ.get("PALLAS_AXON_POOL_IPS"):
        return
    # Parent-process jax state is irrelevant: the execve child re-imports
    # everything fresh under the sanitised environment.
    capman = config.pluginmanager.getplugin("capturemanager")
    if capman is not None:
        try:
            capman.stop_global_capturing()
        except Exception:
            pass

    # Single source of truth for the sanitised env (shared with the driver's
    # multichip dryrun; the module is jax-free so this import cannot hang).
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from __graft_entry__ import _sanitized_env

    env = _sanitized_env(8)

    # The pre-exec interpreter may have opened a connection to the TPU relay
    # (sitecustomize registration). Sockets survive execve unless CLOEXEC —
    # a leaked fd would keep the chip's grant claimed and block every other
    # process. Mark everything above stdio close-on-exec.
    try:
        for fd_name in os.listdir("/proc/self/fd"):
            fd = int(fd_name)
            if fd > 2:
                try:
                    os.set_inheritable(fd, False)
                except OSError:
                    pass
    except OSError:
        pass
    os.execve(sys.executable, [sys.executable, "-m", "pytest", *sys.argv[1:]], env)
