#!/usr/bin/env python
"""Training entry point (name kept for parity with the reference's
`train_agent_apex.py`, BASELINE.json:5 / SURVEY.md §3.1-3.2).

Roles (--role):
  single   one process: act + learn interleaved (reference's 1-actor mode)
  apex     one process driving the whole device mesh: learner cores + actor
           lanes + sharded replay (the TPU-native Ape-X: the pod IS the
           learner and the actor fleet — no Redis, no external processes)
  anakin   single chip, replay in HBM: the fused sample->learn->write-back
           graph of replay/device.py, zero per-step host transfer (same
           algorithm/schedules as `single`; fastest single-chip learner)

The reference selects learner/actor roles per *process* and couples them
through Redis; here the coupling is XLA collectives + host shared memory, so
both roles live in one SPMD program (SURVEY.md §5 "Distributed communication
backend" mapping).  Pod scale: run the same `--role apex` command on every
host with `--process-count N --process-id i --coordinator-address host0:port`
(docs/RUNBOOK.md "Multi-host Ape-X") — jax.distributed replaces the
reference's remote-actor Redis fabric.
"""

import json
import sys

from rainbow_iqn_apex_tpu.config import parse_config


def main(argv=None) -> int:
    cfg = parse_config(argv)
    if cfg.process_count > 1:
        # Pod mode: every host runs this same program (--process-id differs);
        # jax.distributed couples them the way Redis coupled the reference's
        # remote actor processes. Must run BEFORE any jax backend touch.
        from rainbow_iqn_apex_tpu.parallel.multihost import initialize

        initialize(
            cfg.coordinator_address or None, cfg.process_count, cfg.process_id
        )
    if cfg.architecture not in ("iqn", "r2d2"):
        print(
            f"unknown --architecture '{cfg.architecture}' (want 'iqn' or 'r2d2')",
            file=sys.stderr,
        )
        return 2
    if cfg.role == "single" and cfg.architecture == "r2d2":
        from rainbow_iqn_apex_tpu.train_r2d2 import train_r2d2

        summary = train_r2d2(cfg)
    elif cfg.role == "single":
        from rainbow_iqn_apex_tpu.train import train

        summary = train(cfg)
    elif cfg.role == "apex" and cfg.architecture == "r2d2":
        from rainbow_iqn_apex_tpu.parallel.apex_r2d2 import train_apex_r2d2

        summary = train_apex_r2d2(cfg)
    elif cfg.role == "apex":
        from rainbow_iqn_apex_tpu.parallel.apex import train_apex

        summary = train_apex(cfg)
    elif cfg.role == "standby":
        # hot-standby learner (parallel/failover.py; launch_apex.sh
        # --standby): jax-free until it actually claims the learner role,
        # then re-enters the apex entry with --resume auto
        from rainbow_iqn_apex_tpu.parallel.failover import run_standby

        summary = run_standby(cfg)
    elif cfg.role == "anakin" and cfg.architecture == "iqn":
        from rainbow_iqn_apex_tpu.train_anakin import train_anakin

        summary = train_anakin(cfg)
    elif cfg.role == "anakin" and cfg.architecture == "r2d2":
        from rainbow_iqn_apex_tpu.train_anakin_r2d2 import train_anakin_r2d2

        summary = train_anakin_r2d2(cfg)
    else:
        print(
            f"unknown --role '{cfg.role}' (want 'single', 'apex', 'anakin' "
            "or 'standby'; the reference's separate learner/actor processes "
            "are one SPMD program here)",
            file=sys.stderr,
        )
        return 2
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
