# Convenience targets; CI drives the same commands directly.

PY ?= python

.PHONY: test test-fast serve-smoke serve-bench chaos-smoke

# tier-1: fast unit + integration tests on the virtual 8-device CPU mesh
test-fast:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m "not slow"

test:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q

# policy-server smoke: start -> request -> shutdown, in-process transport,
# no network listener — the `serve`-marked subset of tier-1
serve-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_serving.py -q -m serve

# load-generator bench (acceptance: occupancy > 4, zero sheds, swap mid-run)
serve-bench:
	JAX_PLATFORMS=cpu $(PY) scripts/bench_serve.py --clients 64 --requests 2000

# chaos smoke: every named fault-injection point exercised end to end
# (NaN rollback, corrupt-checkpoint fallback, torn-snapshot CRC, retried
# checkpoint IO, stall watchdog, heartbeat loss) — the `chaos`-marked
# subset of tier-1 (docs/RESILIENCE.md)
chaos-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_resilience.py -q -m chaos
