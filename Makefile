# Convenience targets; CI drives the same commands directly.

PY ?= python

.PHONY: test test-fast serve-smoke serve-bench chaos-smoke obs-smoke soak-smoke failover-smoke perf-smoke fleet-smoke quant-smoke trace-smoke multitask-smoke net-smoke replaynet-smoke obsnet-smoke netchaos-smoke league-smoke static-smoke

# tier-1: fast unit + integration tests on the virtual 8-device CPU mesh
test-fast:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m "not slow"

# static-invariant smoke (docs/OBSERVABILITY.md "Static invariants"): the
# `static`-marked analyzer tests (golden fixtures + the finding-free
# meta-test — tier-1 too), then the full-package analyzer run against the
# checked-in EMPTY baseline (exit 1 on any finding).  The CLI deliberately
# imports jax-free — the jax-free checker self-hosts that claim.
static-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_analysis.py -q -m static
	$(PY) scripts/static_analysis.py

test:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q

# policy-server smoke: start -> request -> shutdown, in-process transport,
# no network listener — the `serve`-marked subset of tier-1
serve-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_serving.py -q -m serve

# load-generator bench (acceptance: occupancy > 4, zero sheds, swap mid-run)
serve-bench:
	JAX_PLATFORMS=cpu $(PY) scripts/bench_serve.py --clients 64 --requests 2000

# fleet smoke (docs/SERVING.md "fleet"): the `serve`-marked fleet tests
# (router invariants on real engines) plus the heavy-traffic soak — a
# 2-engine in-process fleet under bursty open-loop arrivals with a slow-
# client cohort, one engine killed cold mid-load (re-route, zero lost
# accepted requests), two weight rollouts (one deliberately backward =
# refused), enforced p99/shed gates — and the run dir must lint as strict
# schema-versioned JSONL (route/scale/rollout rows included)
fleet-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_fleet.py -q -m serve
	rm -rf /tmp/ria_fleet_smoke
	JAX_PLATFORMS=cpu $(PY) scripts/bench_serve.py --fleet-soak \
	  --engines 2 --duration 8 --out /tmp/ria_fleet_smoke
	$(PY) scripts/lint_jsonl.py /tmp/ria_fleet_smoke

# cross-host serving smoke (docs/SERVING.md "cross-host"): the `net`-marked
# unit tests (frame codec hardening, transport/registry/gossip/rollout over
# real loopback sockets — tier-1 too), then the REAL multi-process fleet:
# 2 shared-nothing routers (gossip-federated) over 3 engine-host processes
# discovered purely via lease files, one host SIGKILLed mid-load; gates
# (self-asserted, exit 1): zero lost accepted requests, re-route fired, the
# int8-delta rollout converged on every survivor with BIT-EXACT
# reconstruction asserted by digest, and the run dir lints as strict
# schema-versioned JSONL (route/net/gossip/rollout rows included); then the
# --net soak variant records the wire-rollout byte ratio as one net_soak row
net-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_net.py -q -m net
	rm -rf /tmp/ria_net_smoke
	JAX_PLATFORMS=cpu $(PY) scripts/net_smoke.py --engines 3 --routers 2 \
	  --duration 6 --out /tmp/ria_net_smoke
	$(PY) scripts/lint_jsonl.py /tmp/ria_net_smoke
	rm -rf /tmp/ria_net_soak
	JAX_PLATFORMS=cpu $(PY) scripts/bench_serve.py --fleet-soak --net \
	  --engines 2 --duration 8 --out /tmp/ria_net_soak
	$(PY) scripts/lint_jsonl.py /tmp/ria_net_soak

# cross-host replay smoke (docs/RESILIENCE.md "replay plane"): the
# `net`-marked replay plane tests (framing hoist, append/sample/update
# round trip, bitwise twin + chi-square sampling parity, epoch fencing,
# drop/readmit, step-fenced snapshots — tier-1 too), then the REAL
# multi-process soak: 2 actor hosts + 1 learner + 2 shard-server
# processes discovered purely via lease files, one server SIGKILLed
# mid-load and respawned at a bumped epoch; gates (self-asserted, exit
# 1): the learner never stalls, zero appended-and-acked rows lost on the
# survivor, readmit restores sampling from the revived incarnation, the
# step-fenced server-side snapshot acked — and the run dir lints as
# strict schema-versioned JSONL (replay_net rows included)
replaynet-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_replay_net.py -q -m net
	rm -rf /tmp/ria_replaynet_smoke
	JAX_PLATFORMS=cpu $(PY) scripts/replay_net_smoke.py --duration 12 \
	  --out /tmp/ria_replaynet_smoke
	$(PY) scripts/lint_jsonl.py /tmp/ria_replaynet_smoke

# live-telemetry-plane smoke (docs/OBSERVABILITY.md "Live fleet
# telemetry"): the `obsnet`-marked tests (label escaping, /healthz crash
# path, relay shed-not-stall, fleet fold transitions, alert edges,
# obs_top golden — tier-1 too), then the REAL multi-process soak: 1 obs
# collector + 3 toy trainers discovered purely via lease files, the
# collector SIGKILLed cold mid-load and respawned at a bumped epoch;
# gates (self-asserted, exit 1): training rows never stall, relays
# shed + reconnect, the fleet view re-converges to ok on the NEW
# incarnation — and the run dir lints as strict schema-versioned JSONL
# (obs_net/alert/fleet_health rows included); obs_report must render the
# `obsnet:` section off the soak's rows; then the obs_net_overhead bench
# row must show the relayed learn loop within 3% of the obs_net=False
# default (the never-load-bearing plane's cost gate)
obsnet-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_obs_net.py -q -m obsnet
	rm -rf /tmp/ria_obsnet_smoke
	JAX_PLATFORMS=cpu $(PY) scripts/obs_net_smoke.py --duration 12 \
	  --out /tmp/ria_obsnet_smoke
	$(PY) scripts/lint_jsonl.py /tmp/ria_obsnet_smoke/obs_net_smoke
	$(PY) scripts/obs_report.py /tmp/ria_obsnet_smoke/obs_net_smoke \
	  | tee /tmp/ria_obsnet_smoke/report.txt
	grep -q "obsnet:" /tmp/ria_obsnet_smoke/report.txt
	JAX_PLATFORMS=cpu BENCH_OBSNET_ONLY=1 BENCH_WATCHDOG_SECS=240 \
	  $(PY) bench.py | tee /tmp/ria_obsnet_smoke/bench.jsonl
	$(PY) scripts/lint_jsonl.py /tmp/ria_obsnet_smoke/bench.jsonl
	$(PY) -c "import json; rows = [json.loads(l) for l in \
	  open('/tmp/ria_obsnet_smoke/bench.jsonl') if l.strip()]; \
	  r = [x for x in rows if x.get('path') == 'obs_net_overhead'][-1]; \
	  assert r.get('status') is None, 'obs_net_overhead row: %s' % r['status']; \
	  print('obs_net_overhead: %.2f%% (relayed %.2f vs off %.2f steps/s)' \
	        % (100 * r['value'], r['on_steps_per_sec'], \
	           r['off_steps_per_sec'])); \
	  assert r['value'] <= 0.03, 'obs_net relay overhead above 3%'"

# network-chaos smoke (docs/RESILIENCE.md "degraded network"): the
# `netchaos`-marked tests (spec grammar, seeded determinism, per-fault
# socket semantics, disarmed-identity, plane recovery under injected
# corruption/latency/partition — tier-1 too), then the REAL multi-process
# soak: router + 2 engine hosts, 2 replay shards + learner appenders, obs
# collector, warm standby — all under a seeded rotating fault schedule
# (corruption -> latency+rate-limit -> dual one-way partitions -> heal);
# gates (self-asserted, exit 1): every fault phase actually injected, zero
# lost accepted serve requests, zero acked replay rows lost, NO split
# brain across the asymmetric partition (exactly one learner epoch after
# heal), fleet re-converges within the MTTR bound, chaos rows name the
# injected site — and the run dir lints as strict schema-versioned JSONL;
# then the chaos_overhead bench row gates the DISARMED interposer's seam
# tax on the framed-socket echo path: the seam must either be a VERIFIED
# identity (maybe_wrap returned the socket object unchanged — per-byte
# cost exactly zero by construction) or measure <= 1%; loopback echo
# throughput carries 2-4% per-process placement noise between even
# bitwise-identical arms, so identity is the primary gate and the
# measured ratio is the fallback that any non-identity regression faces
netchaos-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest -q -m netchaos
	rm -rf /tmp/ria_netchaos_smoke
	JAX_PLATFORMS=cpu $(PY) scripts/net_chaos_soak.py \
	  --out /tmp/ria_netchaos_smoke
	$(PY) scripts/lint_jsonl.py /tmp/ria_netchaos_smoke/net_chaos_soak
	JAX_PLATFORMS=cpu BENCH_NETCHAOS_ONLY=1 BENCH_WATCHDOG_SECS=240 \
	  BENCH_CHAOS_REPS=6 BENCH_CHAOS_MAX_REPS=16 \
	  $(PY) bench.py | tee /tmp/ria_netchaos_smoke/bench.jsonl
	$(PY) scripts/lint_jsonl.py /tmp/ria_netchaos_smoke/bench.jsonl
	$(PY) -c "import json; rows = [json.loads(l) for l in \
	  open('/tmp/ria_netchaos_smoke/bench.jsonl') if l.strip()]; \
	  r = [x for x in rows if x.get('path') == 'chaos_overhead'][-1]; \
	  assert r.get('status') is None, 'chaos_overhead row: %s' % r['status']; \
	  print('chaos_overhead: %.2f%% (seamed %.0f vs bare %.0f rt/s, ' \
	        'seam_identity=%s)' % (100 * r['value'], r['on_rtps'], \
	           r['off_rtps'], r.get('seam_identity'))); \
	  assert r.get('seam_identity') or r['value'] <= 0.01, \
	    'disarmed seam is non-identity AND measured tax above 1%'"

# chaos smoke: every named fault-injection point exercised end to end
# (NaN rollback, corrupt-checkpoint fallback, torn-snapshot CRC, retried
# checkpoint IO, stall watchdog, heartbeat loss) — the `chaos`-marked
# subset of tier-1 (docs/RESILIENCE.md)
chaos-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_resilience.py -q -m chaos

# elastic soak smoke: a real multi-process kill/revive schedule must HEAL —
# 2 actor hosts killed, 1 revived (respawn -> lease rejoin -> shard
# readmission), the other evicted after its FailureBudget, stale-epoch spool
# rows fenced, no actor acting past max-weight-lag, final health ok; the
# harness asserts all of it from its own JSONL (docs/RESILIENCE.md).  The
# same path runs tier-1 under the `chaos` marker (tests/test_elastic.py).
soak-smoke:
	rm -rf /tmp/ria_soak_smoke
	JAX_PLATFORMS=cpu $(PY) scripts/chaos_soak.py --frames 2000 \
	  --kill-schedule seeded --out /tmp/ria_soak_smoke
	$(PY) scripts/lint_jsonl.py /tmp/ria_soak_smoke/results

# learner-failover smoke (docs/RESILIENCE.md "learner failover"): the
# failover unit/race tests, then the real-process kill: SIGKILL the toy
# learner mid-run with a live standby — the harness gates that the standby
# claims within the lease timeout, mailbox versions stay strictly monotone
# across the takeover, every adoption is digest-exact (zero stale adopts),
# the successor's post-takeover state is bitwise a plain kill->resume from
# the same checkpoint, and the run dir lints.  Emits one report-only
# failover_mttr bench row.
failover-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_failover.py -q -m chaos
	rm -rf /tmp/ria_failover_smoke
	JAX_PLATFORMS=cpu $(PY) scripts/chaos_soak.py --kill-learner \
	  --out /tmp/ria_failover_smoke
	$(PY) scripts/lint_jsonl.py /tmp/ria_failover_smoke/results

# perf smoke: the pipelined learner hot path (utils/writeback.py ring,
# docs/PERFORMANCE.md) must beat the per-step-sync loop on the CPU synthetic
# apex_loop harness, the device sample frontier (replay/frontier.py) must
# beat the host sum-tree sample path by >= 1.5x on the sample_path micro
# row, the int8-delta weight publish (utils/quantize.py) must ship >= 3x
# fewer bytes/publish than fp32 full on the weight_publish row (decoder
# verified bit-exact inside the row), the fused K-pass clipped replay reuse
# (ops/learn.py, cfg.replay_ratio) must deliver >= 2x learn_steps/s at K=4
# over the emulated actor-bound loop WITH matched-env-frames toy eval
# parity (replay_reuse row — the r05 lesson status guards apply), and the
# bench rows must lint as strict JSON.  Small watchdog: the toy harnesses
# finish in well under a minute per mode.
perf-smoke:
	rm -f /tmp/ria_perf_smoke.jsonl
	JAX_PLATFORMS=cpu BENCH_APEX_ONLY=1 BENCH_WATCHDOG_SECS=420 \
	  $(PY) bench.py | tee /tmp/ria_perf_smoke.jsonl
	$(PY) scripts/lint_jsonl.py /tmp/ria_perf_smoke.jsonl
	$(PY) -c "import json; rows = [json.loads(l) for l in \
	  open('/tmp/ria_perf_smoke.jsonl') if l.strip()]; \
	  r = [x for x in rows if x.get('path') == 'apex_loop'][-1]; \
	  assert r.get('status') is None, 'apex_loop row: %s' % r['status']; \
	  print('apex_loop: depth=%s %.2f steps/s vs depth0 %.2f (speedup %.3f)' \
	        % (r['depth'], r['value'], r['depth0_steps_per_sec'], \
	           r['speedup_vs_depth0'])); \
	  assert r['speedup_vs_depth0'] >= 1.25, 'pipelined loop under 1.25x'; \
	  s = [x for x in rows if x.get('path') == 'sample_path'][-1]; \
	  assert s.get('status') is None, 'sample_path row: %s' % s['status']; \
	  print('sample_path: frontier %.1f batches/s vs host %.1f (speedup %.3f)' \
	        % (s['value'], s['host_batches_per_sec'], s['speedup_vs_host'])); \
	  assert s['speedup_vs_host'] >= 1.5, 'device sample path under 1.5x'; \
	  w = [x for x in rows if x.get('path') == 'weight_publish'][-1]; \
	  assert w.get('status') is None, 'weight_publish row: %s' % w['status']; \
	  print('weight_publish: int8-delta %.0f B/publish vs fp32 %d B (%.2fx)' \
	        % (w['value'], w['fp32_bytes_per_publish'], w['ratio_vs_fp32'])); \
	  assert w['ratio_vs_fp32'] >= 3.0, 'int8-delta publish under 3x vs fp32'; \
	  u = [x for x in rows if x.get('path') == 'replay_reuse'][-1]; \
	  assert u.get('status') is None, 'replay_reuse row: %s' % u['status']; \
	  print('replay_reuse: K=%s %.1f steps/s vs K=1 %.1f (speedup %.3f, ' \
	        'eval %s vs %s, parity=%s)' \
	        % (u['k'], u['value'], u['k1_steps_per_sec'], \
	           u['speedup_vs_k1'], u['eval_k'], u['eval_k1'], \
	           u['eval_parity'])); \
	  assert u['speedup_vs_k1'] >= 2.0, 'replay reuse under 2x at K=4'; \
	  assert u['eval_parity'] is True, 'replay reuse eval parity not shown'; \
	  n = [x for x in rows if x.get('path') == 'replay_net_path'][-1]; \
	  assert n.get('status') is None, 'replay_net_path row: %s' % n['status']; \
	  print('replay_net_path: wire %.1f batches/s vs host %.1f ' \
	        '(ratio %.3f, shm=%s)' \
	        % (n['value'], n['host_batches_per_sec'], \
	           n['ratio_vs_host'], n.get('shm'))); \
	  assert n['ratio_vs_host'] >= 0.5, 'wire replay path under 0.5x of ' \
	        'in-process (shm fast path lost?)'"
	$(PY) scripts/bench_diff.py /tmp/ria_perf_smoke.jsonl

# trace smoke (docs/OBSERVABILITY.md "tracing"): a tiny TRACED apex run
# (trace_sample_every=4) must yield span_link/lag rows that (1) lint as
# strict schema-versioned JSONL, (2) export to VALID Perfetto trace_event
# JSON (cross-host flow events, schema-checked by trace_export --check),
# and (3) drive obs_report to a `critical_path:` stage verdict; then the
# trace_overhead bench row must show the traced learn loop within 3% of
# the untraced one (the always-on-lag + 1-in-N-span overhead gate)
trace-smoke:
	rm -rf /tmp/ria_trace_smoke
	JAX_PLATFORMS=cpu $(PY) train_agent_apex.py --role apex \
	  --env-id toy:catch --compute-dtype float32 --history-length 2 \
	  --hidden-size 64 --num-cosines 16 --num-tau-samples 4 \
	  --num-tau-prime-samples 4 --num-quantile-samples 4 --batch-size 16 \
	  --learning-rate 1e-3 --multi-step 3 --gamma 0.9 --memory-capacity 4096 \
	  --learn-start 512 --frames-per-learn 2 --target-update-period 200 \
	  --num-envs-per-actor 8 --metrics-interval 100 --eval-interval 0 \
	  --checkpoint-interval 0 --eval-episodes 2 --t-max 3072 \
	  --trace-sample-every 4 --weight-publish-interval 200 \
	  --run-id trace_smoke --results-dir /tmp/ria_trace_smoke/results \
	  --checkpoint-dir /tmp/ria_trace_smoke/ckpt
	$(PY) scripts/lint_jsonl.py /tmp/ria_trace_smoke/results/trace_smoke
	$(PY) scripts/trace_export.py /tmp/ria_trace_smoke/results/trace_smoke \
	  -o /tmp/ria_trace_smoke/trace.json --check
	$(PY) scripts/obs_report.py /tmp/ria_trace_smoke/results/trace_smoke \
	  | tee /tmp/ria_trace_smoke/report.txt
	grep -q "critical_path:" /tmp/ria_trace_smoke/report.txt
	JAX_PLATFORMS=cpu BENCH_TRACE_ONLY=1 BENCH_WATCHDOG_SECS=240 \
	  $(PY) bench.py | tee /tmp/ria_trace_smoke/bench.jsonl
	$(PY) scripts/lint_jsonl.py /tmp/ria_trace_smoke/bench.jsonl
	$(PY) -c "import json; rows = [json.loads(l) for l in \
	  open('/tmp/ria_trace_smoke/bench.jsonl') if l.strip()]; \
	  r = [x for x in rows if x.get('path') == 'trace_overhead'][-1]; \
	  assert r.get('status') is None, 'trace_overhead row: %s' % r['status']; \
	  print('trace_overhead: %.2f%% (traced %.2f vs untraced %.2f steps/s)' \
	        % (100 * r['value'], r['traced_steps_per_sec'], \
	           r['untraced_steps_per_sec'])); \
	  assert r['value'] <= 0.03, 'tracing overhead above 3%'"

# quant smoke (docs/PERFORMANCE.md "quantization"): the quantize unit tests
# (codec bit-exactness, delta resync, gate fallback, off-mode bitwise), one
# REAL-engine int8 serve via bench_serve --quant (the agreement gate must
# ACTIVATE the quantized path and both numeric modes must answer the same
# load correctly), and the run dir must lint as strict schema-versioned
# JSONL (quant/quant_fallback/publish rows included)
quant-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_quantize.py -q
	rm -rf /tmp/ria_quant_smoke
	JAX_PLATFORMS=cpu $(PY) scripts/bench_serve.py --quant \
	  --clients 16 --requests 300 --out /tmp/ria_quant_smoke
	$(PY) scripts/lint_jsonl.py /tmp/ria_quant_smoke

# multitask smoke (docs/MULTITASK.md): the `multitask`-marked tests, then a
# seeded 2-game toy apex run that must (1) lint as strict schema-versioned
# JSONL (games/eval_mt rows included), (2) drive obs_report to a `games:`
# per-game section, (3) contain a per-game eval row for BOTH games, and
# (4) record the 2-game-vs-1-game learn-throughput tax as one bench row
multitask-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_multitask.py -q -m multitask
	rm -rf /tmp/ria_mt_smoke
	JAX_PLATFORMS=cpu $(PY) train_agent_apex.py --role apex \
	  --games toy:catch,toy:chain --compute-dtype float32 \
	  --history-length 2 --hidden-size 64 --num-cosines 16 \
	  --num-tau-samples 4 --num-tau-prime-samples 4 \
	  --num-quantile-samples 4 --batch-size 16 --learning-rate 1e-3 \
	  --multi-step 3 --gamma 0.9 --memory-capacity 4096 --learn-start 512 \
	  --frames-per-learn 2 --target-update-period 200 --num-envs-per-actor 8 \
	  --metrics-interval 100 --eval-interval 200 --checkpoint-interval 0 \
	  --eval-episodes 2 --t-max 3072 --run-id mt_smoke \
	  --results-dir /tmp/ria_mt_smoke/results \
	  --checkpoint-dir /tmp/ria_mt_smoke/ckpt
	$(PY) scripts/lint_jsonl.py /tmp/ria_mt_smoke/results/mt_smoke
	$(PY) scripts/obs_report.py /tmp/ria_mt_smoke/results/mt_smoke \
	  | tee /tmp/ria_mt_smoke/report.txt
	grep -q "games:" /tmp/ria_mt_smoke/report.txt
	$(PY) -c "import json; rows = [json.loads(l) for l in \
	  open('/tmp/ria_mt_smoke/results/mt_smoke/metrics.jsonl')]; \
	  games = {r.get('game') for r in rows if r.get('kind') == 'eval'}; \
	  assert games == {'toy:catch', 'toy:chain'}, games; \
	  mt = [r for r in rows if r.get('kind') == 'eval_mt']; \
	  assert mt and mt[-1].get('hn_median') is not None, 'no eval_mt row'; \
	  print('multitask-smoke: per-game eval rows present for', \
	        sorted(games), 'hn_median=%s' % mt[-1]['hn_median'])"
	JAX_PLATFORMS=cpu BENCH_MULTITASK_ONLY=1 BENCH_WATCHDOG_SECS=240 \
	  $(PY) bench.py | tee /tmp/ria_mt_smoke/bench.jsonl
	$(PY) scripts/lint_jsonl.py /tmp/ria_mt_smoke/bench.jsonl
	$(PY) -c "import json; rows = [json.loads(l) for l in \
	  open('/tmp/ria_mt_smoke/bench.jsonl') if l.strip()]; \
	  r = [x for x in rows if x.get('path') == 'multitask_throughput'][-1]; \
	  assert r.get('status') is None, 'multitask_throughput row: %s' % r['status']; \
	  print('multitask_throughput: %.2f steps/s vs single %.2f (ratio %.3f, report-only)' \
	        % (r['value'], r['single_steps_per_sec'], r['ratio_vs_single']))"

# league smoke (docs/LEAGUE.md): the `league`-marked tier-1 tests (seeded
# exploit determinism, bit-exact mailbox-chain copy, fitness ordering with
# missing/NaN evals, respawn keeps member id + generation, default-off
# bitwise parity), then the REAL multi-process soak: a seeded 2-member
# population of genuine toy-scale train() loops under the LeagueController,
# one FORCED truncation exploit; self-asserted gates (exit 1): the loser's
# adopted weights are digest-identical to the winner's published outbox
# reconstruction, the loser's genome was perturbed (not equal to the
# source's), member leases carried member/generation, and the league dir
# lints as strict schema-versioned JSONL; then obs_report must render the
# `league:` per-member section off the controller's rows
league-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_league.py -q -m league
	rm -rf /tmp/ria_league_smoke
	JAX_PLATFORMS=cpu $(PY) scripts/league_soak.py --members 2 \
	  --out /tmp/ria_league_smoke
	$(PY) scripts/lint_jsonl.py /tmp/ria_league_smoke
	$(PY) scripts/obs_report.py /tmp/ria_league_smoke \
	  | tee /tmp/ria_league_smoke/report.txt
	grep -q "league:" /tmp/ria_league_smoke/report.txt

# obs smoke: a short anakin run must yield a lintable, reportable run dir —
# obs_report prints per-role throughput / learn-step percentiles / health,
# lint_jsonl proves every row is strict, schema-versioned JSON
# (docs/OBSERVABILITY.md)
obs-smoke:
	rm -rf /tmp/ria_obs_smoke
	JAX_PLATFORMS=cpu $(PY) train_agent_apex.py --role anakin \
	  --env-id toy:catch --compute-dtype float32 --history-length 2 \
	  --hidden-size 64 --num-cosines 16 --num-tau-samples 4 \
	  --num-tau-prime-samples 4 --num-quantile-samples 4 --batch-size 16 \
	  --learning-rate 1e-3 --multi-step 3 --gamma 0.9 --memory-capacity 4096 \
	  --learn-start 512 --frames-per-learn 2 --target-update-period 200 \
	  --num-envs-per-actor 8 --metrics-interval 200 --eval-interval 0 \
	  --checkpoint-interval 0 --eval-episodes 4 --t-max 2048 \
	  --run-id obs_smoke --results-dir /tmp/ria_obs_smoke/results \
	  --checkpoint-dir /tmp/ria_obs_smoke/ckpt
	$(PY) scripts/obs_report.py /tmp/ria_obs_smoke/results/obs_smoke
	$(PY) scripts/lint_jsonl.py /tmp/ria_obs_smoke/results/obs_smoke
